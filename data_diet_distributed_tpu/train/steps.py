"""Jitted train/eval steps, per-dispatch and chunked (K steps per dispatch).

The reference's hot loop (``trainer/trainer.py:13-35``: zero_grad / forward / CE /
backward / step, one Python iteration per batch with H2D copies) becomes a single
compiled XLA program per step:

* the batch arrives already sharded over the mesh's ``data`` axis; parameters are
  replicated. The compiler inserts the gradient all-reduce over ICI from those
  shardings — the TPU-native equivalent of DDP's bucketed NCCL all-reduce hooks
  (``ddp.py:141``);
* BatchNorm batch statistics are computed over the GLOBAL sharded batch (the reduction
  over a sharded axis lowers to a cross-replica collective), i.e. sync-BN for free —
  strictly stronger than the reference's per-GPU local BN;
* loss and accuracy are mask-weighted so padded rows contribute nothing, and eval
  counts are globally reduced — fixing the reference's per-shard accuracy reporting
  (no all-reduce, ``ddp.py:96-107``; SURVEY §2.4.5);
* the input state is donated — parameters are updated in place in HBM, halving peak
  optimizer memory versus copy-on-update.

The CHUNKED engine (``make_train_chunk`` / ``make_eval_chunk``) compiles K
consecutive steps into ONE dispatch, with the device-resident batch gather
(``data/pipeline.gather_resident_batch``) moved inside the loop: the per-chunk
host→device traffic is a ``[K, B]`` int32 permutation block, and per-step
metrics come back stacked — fetched once, not K times. On the relay-attached
hosts this repo runs on, each dispatch costs ~25 ms (``tools/
profile_dispatch.py`` measures it), so K steps per dispatch divides the
dispatch tax by K.

Bit-exactness contract: chunked training must produce BIT-IDENTICAL results to
the per-step path (``tests/test_chunked.py`` pins it). The scan is therefore
fully unrolled (``unroll=True``): XLA compiles a rolled ``while`` loop body
with different fusion/rounding than the standalone step program (measured ULP
drift on the CPU lane), while the unrolled chunk is the same flat step program
repeated K times — identical math, one dispatch. Unrolling is also why chunk
sizes are clamped (``train/loop.MAX_CHUNK_STEPS``): program size grows with K.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..data.pipeline import gather_resident_batch
from ..obs import registry as obs_registry
from ..obs import xla as obs_xla
from ..ops.scores import cross_entropy
from .state import TrainState


def _batch_key(state, batch):
    """(geometry key, examples-per-dispatch) for the per-dispatch steps."""
    shape = batch["image"].shape
    return shape, shape[0]


def _chunk_key(state, images, labels, indices, idx, mask):
    """(geometry key, examples) for the chunked programs: one compilation per
    distinct chunk length K (idx is [K, B]) and resident geometry."""
    return (idx.shape, images.shape), idx.shape[0] * idx.shape[1]


def _counted(fn, name: str, keyfn=None):
    """Host-side dispatch counter + XLA introspection hook around a jitted
    step: one registry counter increment per CALL (outside the traced
    program — a Python side effect inside it would run once at trace time),
    and — when an ``obs/xla.XlaIntrospector`` is installed — a once-per-
    geometry harvest of the compiled program's cost/memory analysis and
    compile wall-time (``keyfn(*args)`` -> (cheap geometry key, examples)).
    No-op-cheap when nothing is installed; never touches the computation, so
    the chunked engine's bit-exactness contract is untouched."""
    counter = f"dispatches_{name}"

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        obs_registry.inc(counter)
        if keyfn is not None and obs_xla.current() is not None:
            key, examples = keyfn(*args)
            obs_xla.harvest(name, fn, args, kwargs, key, examples)
        return fn(*args, **kwargs)

    return dispatch


def _train_step_math(model, augment, state: TrainState, batch,
                     update_sharding=None):
    """One optimizer step — THE training math, shared verbatim by the
    per-dispatch step and the chunked scan body so the two cannot drift.

    ``update_sharding`` (a ``parallel/mesh.UpdateSharding``, None = the
    replicated baseline) arms the cross-replica SHARDED weight update:
    gradients are constrained to the data-axis-sharded layout — GSPMD then
    lowers the gradient reduction to a reduce-SCATTER instead of an
    all-reduce — and the optimizer update (sharded grads x sharded slots)
    runs on each replica's parameter shard only. The updated params are
    re-pinned to the sharded layout and STAY sharded between steps: the
    weight all-gather happens at use inside the next forward, which is where
    it both overlaps (per-layer, under the latency-hiding scheduler) and
    stays bit-exact — an end-of-step re-replication constraint measurably
    reorders the backward's reductions (~3e-8 on the CPU lane), while this
    formulation is tree-equal bit-identical to the baseline (pinned)."""
    mask = batch["mask"]
    image = batch["image"]
    if augment is not None:
        from ..data.augment import augment_images
        image = augment_images(state.step, image, crop_pad=augment[0],
                               flip=augment[1], seed=augment[2])

    def loss_fn(params):
        logits, updates = model.apply(
            {"params": params, "batch_stats": state.batch_stats},
            image, train=True, mutable=["batch_stats"])
        per_ex = cross_entropy(logits, batch["label"]) * mask
        loss = jnp.sum(per_ex) / jnp.maximum(jnp.sum(mask), 1.0)
        return loss, (logits, updates["batch_stats"])

    (loss, (logits, new_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    if update_sharding is not None:
        grads = update_sharding.shard(grads)   # <- the reduce-scatter point
    state = state.apply_gradients(grads=grads, batch_stats=new_stats)
    if update_sharding is not None:
        # Pin the layout (no numeric effect: propagation already leaves the
        # updated shards in place) so the between-steps residency of params
        # is the sharded-update layout by construction, not by inference.
        state = state.replace(params=update_sharding.shard(state.params))
    correct = jnp.sum((jnp.argmax(logits, -1) == batch["label"]) * mask)
    metrics = {"loss": loss, "correct": correct, "examples": jnp.sum(mask)}
    return state, metrics


def _eval_step_math(model, state: TrainState, batch):
    mask = batch["mask"]
    logits = model.apply(state.variables, batch["image"], train=False)
    per_ex = cross_entropy(logits, batch["label"]) * mask
    correct = jnp.sum((jnp.argmax(logits, -1) == batch["label"]) * mask)
    return {"loss_sum": jnp.sum(per_ex), "correct": correct,
            "examples": jnp.sum(mask)}


# functools.cache: Flax modules are frozen dataclasses (hashable by config), so the
# same model config returns the SAME jitted step — repeated fits (multi-seed scoring
# pretrains 10 models) hit the jit cache instead of recompiling per seed.
# ``augment`` is a hashable (crop_pad, flip, seed) tuple (None = off) for the
# same reason; the seed in the tuple means augmented multi-seed pretrains
# recompile per seed — see data/augment.py for why that trade is taken.
@functools.cache
def make_train_step(model, augment: tuple[int, bool, int] | None = None,
                    update_sharding=None):
    def train_step(state: TrainState, batch):
        return _train_step_math(model, augment, state, batch, update_sharding)

    return _counted(jax.jit(train_step, donate_argnums=(0,)), "train_step",
                    keyfn=_batch_key)


@functools.cache
def make_train_chunk(model, augment: tuple[int, bool, int] | None = None,
                     out_sharding=None, update_sharding=None):
    """K consecutive train steps as ONE dispatch (K = ``idx.shape[0]``, a
    shape — one compilation per distinct chunk length, i.e. the epoch body
    plus at most one tail).

    ``train_chunk(state, images, labels, indices, idx, mask)``: the resident
    arrays stay on device across chunks; ``idx``/``mask`` are ``[K, B]``
    blocks from ``ResidentBatches.chunk_indices``. The gather runs INSIDE the
    chunk, so the dispatch's host-side input is just the permutation block.
    Returns ``(state, metrics)`` with per-step metrics stacked to ``[K]`` —
    kept per-step (not reduced on device) so the host computes the epoch
    record from exactly the same scalars, in the same order, as the per-step
    path: bit-identical history is the engine's correctness contract.
    ``out_sharding`` (hashable ``NamedSharding``) is the resident gather's
    data-axis layout constraint. State is donated through the scan.
    ``update_sharding`` arms the cross-replica sharded weight update inside
    the scan body (the same hashable handle as ``make_train_step`` — see
    ``_train_step_math``).

    Like ``make_train_step``, the ``augment`` tuple embeds the training seed,
    so augmented MULTI-SEED scoring pretrains compile one chunk per seed —
    the per-step path's documented trade (data/augment.py), amplified here by
    the unrolled program size. Accepted deliberately: threading the seed in
    as a traced operand would fork the augment plumbing between the two
    engines, weakening the shared-math property the bit-exactness contract
    rests on, to optimize a rare configuration (augmentation during short
    scoring pretrains).
    """
    def train_chunk(state: TrainState, images, labels, indices, idx, mask):
        def body(carry, xs):
            take, m = xs
            batch = gather_resident_batch(images, labels, indices, take, m,
                                          out_sharding)
            return _train_step_math(model, augment, carry, batch,
                                    update_sharding)

        if idx.shape[0] == 1:
            # A length-1 scan — an epoch tail — compiles with different
            # rounding than the bare step program even unrolled (measured on
            # the CPU lane); the bare fused gather+step is bit-identical, so
            # the tail takes it directly.
            state, metrics = body(state, (idx[0], mask[0]))
            return state, {k: v[None] for k, v in metrics.items()}
        # unroll=True: see module docstring — a rolled loop body compiles with
        # different rounding than the per-dispatch step; the unrolled chunk is
        # the identical step program repeated, so chunked == per-step bitwise.
        return jax.lax.scan(body, state, (idx, mask), unroll=True)

    return _counted(jax.jit(train_chunk, donate_argnums=(0,)), "train_chunk",
                    keyfn=_chunk_key)


@functools.cache
def make_eval_chunk(model, out_sharding=None):
    """K eval batches per dispatch over the resident arrays — the eval-side
    twin of ``make_train_chunk`` (same gather, same unroll-for-exactness);
    returns the per-batch sum dicts stacked to ``[K]`` for a single fetch."""
    def eval_chunk(state: TrainState, images, labels, indices, idx, mask):
        def body(carry, xs):
            take, m = xs
            batch = gather_resident_batch(images, labels, indices, take, m,
                                          out_sharding)
            return carry, _eval_step_math(model, state, batch)

        if idx.shape[0] == 1:   # length-1 scan ≠ bare step bitwise; see above
            _, out = body(0, (idx[0], mask[0]))
            return {k: v[None] for k, v in out.items()}
        _, out = jax.lax.scan(body, 0, (idx, mask), unroll=True)
        return out

    return _counted(jax.jit(eval_chunk), "eval_chunk", keyfn=_chunk_key)


@functools.cache
def make_eval_step(model):
    def eval_step(state: TrainState, batch):
        return _eval_step_math(model, state, batch)

    return _counted(jax.jit(eval_step), "eval_step", keyfn=_batch_key)
