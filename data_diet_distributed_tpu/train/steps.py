"""Jitted train/eval steps.

The reference's hot loop (``trainer/trainer.py:13-35``: zero_grad / forward / CE /
backward / step, one Python iteration per batch with H2D copies) becomes a single
compiled XLA program per step:

* the batch arrives already sharded over the mesh's ``data`` axis; parameters are
  replicated. The compiler inserts the gradient all-reduce over ICI from those
  shardings — the TPU-native equivalent of DDP's bucketed NCCL all-reduce hooks
  (``ddp.py:141``);
* BatchNorm batch statistics are computed over the GLOBAL sharded batch (the reduction
  over a sharded axis lowers to a cross-replica collective), i.e. sync-BN for free —
  strictly stronger than the reference's per-GPU local BN;
* loss and accuracy are mask-weighted so padded rows contribute nothing, and eval
  counts are globally reduced — fixing the reference's per-shard accuracy reporting
  (no all-reduce, ``ddp.py:96-107``; SURVEY §2.4.5);
* the input state is donated — parameters are updated in place in HBM, halving peak
  optimizer memory versus copy-on-update.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops.scores import cross_entropy
from .state import TrainState


# functools.cache: Flax modules are frozen dataclasses (hashable by config), so the
# same model config returns the SAME jitted step — repeated fits (multi-seed scoring
# pretrains 10 models) hit the jit cache instead of recompiling per seed.
# ``augment`` is a hashable (crop_pad, flip, seed) tuple (None = off) for the
# same reason; the seed in the tuple means augmented multi-seed pretrains
# recompile per seed — see data/augment.py for why that trade is taken.
@functools.cache
def make_train_step(model, augment: tuple[int, bool, int] | None = None):
    def train_step(state: TrainState, batch):
        mask = batch["mask"]
        image = batch["image"]
        if augment is not None:
            from ..data.augment import augment_images
            image = augment_images(state.step, image, crop_pad=augment[0],
                                   flip=augment[1], seed=augment[2])

        def loss_fn(params):
            logits, updates = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                image, train=True, mutable=["batch_stats"])
            per_ex = cross_entropy(logits, batch["label"]) * mask
            loss = jnp.sum(per_ex) / jnp.maximum(jnp.sum(mask), 1.0)
            return loss, (logits, updates["batch_stats"])

        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        correct = jnp.sum((jnp.argmax(logits, -1) == batch["label"]) * mask)
        metrics = {"loss": loss, "correct": correct, "examples": jnp.sum(mask)}
        return state, metrics

    return jax.jit(train_step, donate_argnums=(0,))


@functools.cache
def make_eval_step(model):
    def eval_step(state: TrainState, batch):
        mask = batch["mask"]
        logits = model.apply(state.variables, batch["image"], train=False)
        per_ex = cross_entropy(logits, batch["label"]) * mask
        correct = jnp.sum((jnp.argmax(logits, -1) == batch["label"]) * mask)
        return {"loss_sum": jnp.sum(per_ex), "correct": correct,
                "examples": jnp.sum(mask)}

    return jax.jit(eval_step)
