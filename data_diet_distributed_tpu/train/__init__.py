from .loop import FitResult, evaluate, fit, run_datadiet, score_variables_for_seeds
from .state import TrainState, create_train_state, make_optimizer
from .steps import make_eval_step, make_train_step

__all__ = [
    "FitResult", "TrainState", "create_train_state", "evaluate", "fit",
    "make_eval_step", "make_optimizer", "make_train_step", "run_datadiet",
    "score_variables_for_seeds",
]
