"""Per-example importance-score kernels: EL2N and GraNd.

EL2N (reference: ``get_scores_and_prune.py:15-18``): ``‖softmax(f(x)) − onehot(y)‖₂``.
GraNd (Paul et al. 2021; ABSENT from the reference): ``‖∇_θ ℓ(f(x), y)‖₂`` per example.

TPU-first design decisions:

* scoring runs in **eval mode** (frozen BatchNorm statistics) — the reference
  accidentally scored in train mode, mutating running stats (SURVEY §2.4.1);
* the dataset pass is sharded over the mesh's ``data`` axis via ``shard_map`` — every
  device scores its shard concurrently, where the reference scored the whole set on
  one GPU (``ddp.py:56``);
* full GraNd is a ``vmap(grad)`` per-example backward, chunked with ``lax.map`` inside
  ``shard_map`` so peak memory is ``chunk`` gradients per device while the MXU still
  sees batched convs;
* last-layer GraNd is closed-form — for a linear classifier ``z = W h + b``,
  ``∂ℓ/∂W = (p − y) hᵀ`` and ``∂ℓ/∂b = p − y``, so the norm is
  ``‖p − y‖ · sqrt(‖h‖² + 1)`` with no backward pass at all;
* the EL2N / last-layer-GraNd epilogues have fused Pallas kernels
  (``pallas_kernels.py``), selected by ``use_pallas`` (auto-on for TPU backends).
  ``pallas_call`` is not GSPMD-partitionable, which is one more reason the mesh
  path uses ``shard_map``: each device invokes the kernel on its local shard.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from .pallas_kernels import el2n_pallas, grand_last_layer_pallas

# shard_map moved to the jax top level (with check_vma) after 0.4.x, where it
# lives under jax.experimental (with check_rep). Bind one callable with
# replication/VMA checking OFF either way: jax.grad taken INSIDE the body
# w.r.t. replicated (P()) params would otherwise auto-insert a psum over
# 'data' — summing per-example gradients across devices (see _wrap).
if hasattr(jax, "shard_map"):
    _shard_map = partial(jax.shard_map, check_vma=False)
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _experimental_shard_map
    _shard_map = partial(_experimental_shard_map, check_rep=False)


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example CE loss, [B] <- logits [B, C], labels [B]."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def el2n_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """EL2N score per example: L2 error of the softmax vector vs the one-hot target."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    err = probs - jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return jnp.sqrt(jnp.sum(err * err, axis=-1))


def margin_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Margin difficulty per example: ``max_{k≠y} p_k − p_y`` ∈ [−1, 1].

    The classic uncertainty-margin baseline, oriented so HIGHER = harder
    (matches the keep-hardest pruning default, like EL2N/GraNd): confidently
    correct examples score near −1, confused/mislabeled ones near +1."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    p_true = jnp.sum(probs * onehot, axis=-1)
    p_other = jnp.max(probs - onehot, axis=-1)   # onehot subtraction masks y
    return p_other - p_true


def grand_last_layer_from_logits(logits: jax.Array, features: jax.Array,
                                 labels: jax.Array) -> jax.Array:
    """Exact GraNd restricted to the classifier layer, no backward needed."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    err = probs - jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    err_sq = jnp.sum(err * err, axis=-1)
    feat_sq = jnp.sum(features.astype(jnp.float32) ** 2, axis=-1)
    return jnp.sqrt(err_sq * (feat_sq + 1.0))


def _forward(model, variables, images, *, eval_mode: bool, capture_features=False):
    """Scoring forward pass. ``eval_mode=False`` reproduces the reference's accidental
    train-mode scoring (BatchNorm normalizes by BATCH statistics instead of running
    averages — ``get_scores_and_prune.py:8-20``, SURVEY §2.4.1) for A/B parity
    studies; the stat updates themselves are discarded, never persisted."""
    if eval_mode:
        return model.apply(variables, images, train=False,
                           capture_features=capture_features)
    out, _ = model.apply(variables, images, train=True,
                         capture_features=capture_features,
                         mutable=["batch_stats"])
    return out


def _wrap(local_scores, mesh: Mesh | None):
    """Lift a per-device ``(variables, image, label, mask) -> scores`` function to a
    jitted whole-batch step, sharded over the FLATTENED mesh (every axis, ``data``
    first) when a multi-device mesh is given: per-example scoring has no
    tensor-parallel compute worth keeping a ``model`` axis idle for, so on a TP
    mesh all ``data x model`` devices score distinct examples. Params enter with
    in_spec ``P()`` — jit re-replicates a TP-sharded classifier once per pass
    (~MBs over ICI, amortized over the whole dataset).

    check_vma=False on the shard_map: with VMA tracking on, ``jax.grad`` taken INSIDE
    the body w.r.t. the replicated (P()) params auto-inserts a psum over 'data' to
    keep the cotangent replicated — summing each position's per-example gradients
    ACROSS devices. These are per-example scores, not a data-parallel update: the
    body is fully local math and must stay that way. (It also lets the body invoke
    Pallas kernels, which GSPMD could not partition.)
    """
    if mesh is None or mesh.size == 1:
        @jax.jit
        def step(variables, batch):
            return local_scores(variables, batch["image"], batch["label"],
                                batch["mask"])
        return step

    from ..parallel.mesh import flat_batch_spec
    spec = flat_batch_spec(mesh)
    sharded = _shard_map(
        local_scores, mesh=mesh,
        in_specs=(P(), spec, spec, spec),
        out_specs=spec)

    @jax.jit
    def step(variables, batch):
        return sharded(variables, batch["image"], batch["label"], batch["mask"])

    return step


def resolve_use_pallas(use_pallas: bool | None) -> bool:
    """None -> auto: fused kernels on TPU, plain XLA elsewhere (the kernels still
    run everywhere via interpret mode, but interpreted kernels are slower than XLA)."""
    return jax.default_backend() == "tpu" if use_pallas is None else use_pallas


@functools.cache
def make_el2n_step(model, mesh: Mesh | None = None, eval_mode: bool = True,
                   use_pallas: bool | None = None):
    """Forward-only EL2N over a (possibly mesh-sharded) batch."""
    use_pallas = resolve_use_pallas(use_pallas)

    def local_scores(variables, image, label, mask):
        logits = _forward(model, variables, image, eval_mode=eval_mode)
        if use_pallas:
            return el2n_pallas(logits, label, mask)
        return el2n_from_logits(logits, label) * mask

    return _wrap(local_scores, mesh)


@functools.cache
def make_margin_step(model, mesh: Mesh | None = None, eval_mode: bool = True):
    """Forward-only margin difficulty over a (possibly mesh-sharded) batch."""

    def local_scores(variables, image, label, mask):
        logits = _forward(model, variables, image, eval_mode=eval_mode)
        return margin_from_logits(logits, label) * mask

    return _wrap(local_scores, mesh)


@functools.cache
def make_correctness_step(model, mesh: Mesh | None = None,
                          eval_mode: bool = True):
    """Per-example 0/1 correctness [B] over a (possibly mesh-sharded) batch —
    the per-epoch signal the forgetting-events score accumulates
    (``ops/forgetting.ForgettingTracker``). Padded rows report 0."""

    def local_scores(variables, image, label, mask):
        logits = _forward(model, variables, image, eval_mode=eval_mode)
        return (jnp.argmax(logits, -1) == label).astype(jnp.float32) * mask

    return _wrap(local_scores, mesh)


@functools.cache
def make_grand_last_layer_step(model, mesh: Mesh | None = None,
                               eval_mode: bool = True,
                               use_pallas: bool | None = None):
    use_pallas = resolve_use_pallas(use_pallas)

    def local_scores(variables, image, label, mask):
        logits, feats = _forward(model, variables, image,
                                 eval_mode=eval_mode, capture_features=True)
        if use_pallas:
            # The fused kernel redoes the classifier matmul in VMEM; the model's
            # logits are unused here and DCE'd, so the matmul still runs once.
            head = variables["params"]["classifier"]
            return grand_last_layer_pallas(feats, head["kernel"], head["bias"],
                                           label, mask)
        return grand_last_layer_from_logits(logits, feats, label) * mask

    return _wrap(local_scores, mesh)


@functools.cache
def make_grand_step(model, mesh: Mesh | None = None, chunk: int = 32,
                    eval_mode: bool = True,
                    use_pallas: bool | None = None):
    """Full GraNd: per-example gradient norm over ALL parameters.

    Inside ``shard_map`` each device sees its local slice of the batch; the slice is
    reshaped to ``[n_chunks, chunk]`` and ``lax.map`` runs a ``vmap`` of single-example
    grads per chunk, reducing each gradient to its global norm immediately so at most
    ``chunk`` gradient pytrees are live per device.
    """

    def per_example_norm(variables, image, label):
        rest = {k: v for k, v in variables.items() if k != "params"}

        def loss_fn(params):
            logits = _forward(model, {"params": params, **rest}, image[None],
                              eval_mode=eval_mode)
            return cross_entropy(logits, label[None])[0]

        grads = jax.grad(loss_fn)(variables["params"])
        return optax.global_norm(grads)

    def local_scores(variables, image, label, mask):
        n = image.shape[0]
        c = min(chunk, n)
        if n % c != 0:  # static shapes: pad local slice up to a chunk multiple
            pad = c - n % c
            image = jnp.concatenate([image, jnp.zeros((pad, *image.shape[1:]),
                                                      image.dtype)])
            label = jnp.concatenate([label, jnp.zeros((pad,), label.dtype)])
        imgs = image.reshape(-1, c, *image.shape[1:])
        labs = label.reshape(-1, c)
        norms = jax.lax.map(
            lambda xs: jax.vmap(partial(per_example_norm, variables))(*xs),
            (imgs, labs))
        return norms.reshape(-1)[:n] * mask

    return _wrap(local_scores, mesh)


@functools.cache
def make_grand_batched_step(model, mesh: Mesh | None = None,
                            use_pallas: bool | None = None):
    """Full GraNd via the batched exact algorithm (``grand_batched.py``): one
    batched forward + one backward w.r.t. per-layer output perturbations, then
    closed-form per-layer norm contractions — no per-example backwards, so the
    MXU sees large batched matmuls instead of batch-1 convolutions. Eval-mode
    only (train-mode BatchNorm couples examples; see the module docstring).
    ``use_pallas`` selects the fused conv-grad-norm kernel for the large-S
    conv layers (None = auto: on for TPU backends). ``DDT_GRAND_FUSED=1``
    routes through ``batched_grand_scores_fused`` (contractions inside the
    backward pass) instead of the two-phase composition."""
    from . import grand_batched
    use_pallas = resolve_use_pallas(use_pallas)
    # Module-attribute access (not by-name import): the toggle is resolved at
    # factory-call time. Only env-pinned subprocesses can rely on it — this
    # factory is functools.cache'd, so in-process patching of FUSED_BWD after
    # a first call returns the previously-cached path (tests call the score
    # functions directly for exactly that reason; see tests/test_grand_batched.py).
    score_fn = (grand_batched.batched_grand_scores_fused
                if grand_batched.FUSED_BWD
                else grand_batched.batched_grand_scores)

    def local_scores(variables, image, label, mask):
        return score_fn(model, variables, image, label, mask,
                        use_pallas=use_pallas)

    return _wrap(local_scores, mesh)


@functools.cache
def make_score_step(model, method: str, mesh: Mesh | None = None, chunk: int = 32,
                    eval_mode: bool = True, use_pallas: bool | None = None):
    """Factory keyed by config string (el2n | margin | grand | grand_vmap |
    grand_last_layer). ``grand`` runs the batched exact algorithm in eval mode
    and falls back to ``vmap(grad)`` for train-mode (reference-quirk) scoring;
    ``grand_vmap`` forces the naive path (cross-checking, exotic layers)."""
    if method == "el2n":
        return make_el2n_step(model, mesh, eval_mode=eval_mode,
                              use_pallas=use_pallas)
    if method == "margin":
        return make_margin_step(model, mesh, eval_mode=eval_mode)
    if method == "grand":
        if eval_mode:
            return make_grand_batched_step(model, mesh, use_pallas=use_pallas)
        return make_grand_step(model, mesh, chunk=chunk, eval_mode=eval_mode,
                               use_pallas=use_pallas)
    if method == "grand_vmap":
        return make_grand_step(model, mesh, chunk=chunk, eval_mode=eval_mode,
                               use_pallas=use_pallas)
    if method == "grand_last_layer":
        return make_grand_last_layer_step(model, mesh, eval_mode=eval_mode,
                                          use_pallas=use_pallas)
    raise ValueError(f"unknown score method {method!r}")
