"""Per-example importance-score kernels: EL2N and GraNd.

EL2N (reference: ``get_scores_and_prune.py:15-18``): ``‖softmax(f(x)) − onehot(y)‖₂``.
GraNd (Paul et al. 2021; ABSENT from the reference): ``‖∇_θ ℓ(f(x), y)‖₂`` per example.

TPU-first design decisions:

* scoring runs in **eval mode** (frozen BatchNorm statistics) — the reference
  accidentally scored in train mode, mutating running stats (SURVEY §2.4.1);
* the dataset pass is sharded over the mesh's ``data`` axis via ``shard_map`` — every
  device scores its shard concurrently, where the reference scored the whole set on
  one GPU (``ddp.py:56``);
* full GraNd is a ``vmap(grad)`` per-example backward, chunked with ``lax.map`` inside
  ``shard_map`` so peak memory is ``chunk`` gradients per device while the MXU still
  sees batched convs;
* last-layer GraNd is closed-form — for a linear classifier ``z = W h + b``,
  ``∂ℓ/∂W = (p − y) hᵀ`` and ``∂ℓ/∂b = p − y``, so the norm is
  ``‖p − y‖ · sqrt(‖h‖² + 1)`` with no backward pass at all;
* the EL2N / last-layer-GraNd epilogues have fused Pallas kernels
  (``pallas_kernels.py``), selected by ``use_pallas`` (auto-on for TPU backends).
  ``pallas_call`` is not GSPMD-partitionable, which is one more reason the mesh
  path uses ``shard_map``: each device invokes the kernel on its local shard.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from .pallas_kernels import el2n_pallas, grand_last_layer_pallas

# shard_map moved to the jax top level (with check_vma) after 0.4.x, where it
# lives under jax.experimental (with check_rep). Bind one callable with
# replication/VMA checking OFF either way: jax.grad taken INSIDE the body
# w.r.t. replicated (P()) params would otherwise auto-insert a psum over
# 'data' — summing per-example gradients across devices (see _wrap).
if hasattr(jax, "shard_map"):
    _shard_map = partial(jax.shard_map, check_vma=False)
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _experimental_shard_map
    _shard_map = partial(_experimental_shard_map, check_rep=False)


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-example CE loss, [B] <- logits [B, C], labels [B]."""
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def el2n_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """EL2N score per example: L2 error of the softmax vector vs the one-hot target."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    err = probs - jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return jnp.sqrt(jnp.sum(err * err, axis=-1))


def margin_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Margin difficulty per example: ``max_{k≠y} p_k − p_y`` ∈ [−1, 1].

    The classic uncertainty-margin baseline, oriented so HIGHER = harder
    (matches the keep-hardest pruning default, like EL2N/GraNd): confidently
    correct examples score near −1, confused/mislabeled ones near +1."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    p_true = jnp.sum(probs * onehot, axis=-1)
    p_other = jnp.max(probs - onehot, axis=-1)   # onehot subtraction masks y
    return p_other - p_true


def grand_last_layer_from_logits(logits: jax.Array, features: jax.Array,
                                 labels: jax.Array) -> jax.Array:
    """Exact GraNd restricted to the classifier layer, no backward needed."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    err = probs - jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    err_sq = jnp.sum(err * err, axis=-1)
    feat_sq = jnp.sum(features.astype(jnp.float32) ** 2, axis=-1)
    return jnp.sqrt(err_sq * (feat_sq + 1.0))


def _forward(model, variables, images, *, eval_mode: bool, capture_features=False):
    """Scoring forward pass. ``eval_mode=False`` reproduces the reference's accidental
    train-mode scoring (BatchNorm normalizes by BATCH statistics instead of running
    averages — ``get_scores_and_prune.py:8-20``, SURVEY §2.4.1) for A/B parity
    studies; the stat updates themselves are discarded, never persisted."""
    if eval_mode:
        return model.apply(variables, images, train=False,
                           capture_features=capture_features)
    out, _ = model.apply(variables, images, train=True,
                         capture_features=capture_features,
                         mutable=["batch_stats"])
    return out


def _wrap(local_scores, mesh: Mesh | None):
    """Lift a per-device ``(variables, image, label, mask) -> scores`` function to a
    jitted whole-batch step, sharded over the FLATTENED mesh (every axis, ``data``
    first) when a multi-device mesh is given: per-example scoring has no
    tensor-parallel compute worth keeping a ``model`` axis idle for, so on a TP
    mesh all ``data x model`` devices score distinct examples. Params enter with
    in_spec ``P()`` — jit re-replicates a TP-sharded classifier once per pass
    (~MBs over ICI, amortized over the whole dataset).

    check_vma=False on the shard_map: with VMA tracking on, ``jax.grad`` taken INSIDE
    the body w.r.t. the replicated (P()) params auto-inserts a psum over 'data' to
    keep the cotangent replicated — summing each position's per-example gradients
    ACROSS devices. These are per-example scores, not a data-parallel update: the
    body is fully local math and must stay that way. (It also lets the body invoke
    Pallas kernels, which GSPMD could not partition.)
    """
    if mesh is None or mesh.size == 1:
        @jax.jit
        def step(variables, batch):
            return local_scores(variables, batch["image"], batch["label"],
                                batch["mask"])
        return step

    from ..parallel.mesh import flat_batch_spec
    spec = flat_batch_spec(mesh)
    sharded = _shard_map(
        local_scores, mesh=mesh,
        in_specs=(P(), spec, spec, spec),
        out_specs=spec)

    @jax.jit
    def step(variables, batch):
        return sharded(variables, batch["image"], batch["label"], batch["mask"])

    return step


def resolve_use_pallas(use_pallas: bool | None) -> bool:
    """None -> auto: fused kernels on TPU, plain XLA elsewhere (the kernels still
    run everywhere via interpret mode, but interpreted kernels are slower than XLA)."""
    return jax.default_backend() == "tpu" if use_pallas is None else use_pallas


def make_local_scores(model, method: str, *, chunk: int = 32,
                      eval_mode: bool = True, use_pallas: bool = False):
    """The per-device ``(variables, image, label, mask) -> scores [B]``
    function for ``method`` — the ONE definition shared by the per-batch step
    factories (``_wrap``-ed below) and the chunked score engine
    (``make_score_chunk``), so the two engines execute the same score math
    and cannot drift. ``use_pallas`` must already be resolved (bool)."""
    if method == "el2n":
        def local_scores(variables, image, label, mask):
            logits = _forward(model, variables, image, eval_mode=eval_mode)
            if use_pallas:
                return el2n_pallas(logits, label, mask)
            return el2n_from_logits(logits, label) * mask
        return local_scores

    if method == "margin":
        def local_scores(variables, image, label, mask):
            logits = _forward(model, variables, image, eval_mode=eval_mode)
            return margin_from_logits(logits, label) * mask
        return local_scores

    if method == "correctness":
        def local_scores(variables, image, label, mask):
            logits = _forward(model, variables, image, eval_mode=eval_mode)
            return (jnp.argmax(logits, -1) == label).astype(jnp.float32) * mask
        return local_scores

    if method == "grand_last_layer":
        def local_scores(variables, image, label, mask):
            logits, feats = _forward(model, variables, image,
                                     eval_mode=eval_mode,
                                     capture_features=True)
            if use_pallas:
                # The fused kernel redoes the classifier matmul in VMEM; the
                # model's logits are unused here and DCE'd, so the classifier
                # matmul still happens exactly once.
                head = variables["params"]["classifier"]
                return grand_last_layer_pallas(feats, head["kernel"],
                                               head["bias"], label, mask)
            return grand_last_layer_from_logits(logits, feats, label) * mask
        return local_scores

    if method == "grand_batched":
        from . import grand_batched
        # Module-attribute access (not by-name import): the composition
        # toggles are resolved at factory-call time. Only env-pinned
        # subprocesses can rely on them — the step factories are
        # functools.cache'd, so in-process patching after a first call
        # returns the previously-cached path (tests call the score functions
        # directly for exactly that reason; tests/test_grand_batched.py).
        if grand_batched.MEGAKERNEL:
            score_fn = partial(grand_batched.batched_grand_scores_fused,
                               megakernel=True)
        elif grand_batched.FUSED_BWD:
            score_fn = grand_batched.batched_grand_scores_fused
        else:
            score_fn = grand_batched.batched_grand_scores

        def local_scores(variables, image, label, mask):
            return score_fn(model, variables, image, label, mask,
                            use_pallas=use_pallas)
        return local_scores

    if method == "grand_vmap":
        def per_example_norm(variables, image, label):
            rest = {k: v for k, v in variables.items() if k != "params"}

            def loss_fn(params):
                logits = _forward(model, {"params": params, **rest},
                                  image[None], eval_mode=eval_mode)
                return cross_entropy(logits, label[None])[0]

            grads = jax.grad(loss_fn)(variables["params"])
            return optax.global_norm(grads)

        def local_scores(variables, image, label, mask):
            n = image.shape[0]
            c = min(chunk, n)
            if n % c != 0:  # static shapes: pad slice up to a chunk multiple
                pad = c - n % c
                image = jnp.concatenate(
                    [image, jnp.zeros((pad, *image.shape[1:]), image.dtype)])
                label = jnp.concatenate(
                    [label, jnp.zeros((pad,), label.dtype)])
            imgs = image.reshape(-1, c, *image.shape[1:])
            labs = label.reshape(-1, c)
            norms = jax.lax.map(
                lambda xs: jax.vmap(partial(per_example_norm, variables))(*xs),
                (imgs, labs))
            return norms.reshape(-1)[:n] * mask
        return local_scores

    raise ValueError(f"unknown score method {method!r}")


@functools.cache
def make_el2n_step(model, mesh: Mesh | None = None, eval_mode: bool = True,
                   use_pallas: bool | None = None):
    """Forward-only EL2N over a (possibly mesh-sharded) batch."""
    return _wrap(make_local_scores(
        model, "el2n", eval_mode=eval_mode,
        use_pallas=resolve_use_pallas(use_pallas)), mesh)


@functools.cache
def make_margin_step(model, mesh: Mesh | None = None, eval_mode: bool = True):
    """Forward-only margin difficulty over a (possibly mesh-sharded) batch."""
    return _wrap(make_local_scores(model, "margin", eval_mode=eval_mode), mesh)


@functools.cache
def make_correctness_step(model, mesh: Mesh | None = None,
                          eval_mode: bool = True):
    """Per-example 0/1 correctness [B] over a (possibly mesh-sharded) batch —
    the per-epoch signal the forgetting-events score accumulates
    (``ops/forgetting.ForgettingTracker``). Padded rows report 0."""
    return _wrap(make_local_scores(model, "correctness", eval_mode=eval_mode),
                 mesh)


@functools.cache
def make_grand_last_layer_step(model, mesh: Mesh | None = None,
                               eval_mode: bool = True,
                               use_pallas: bool | None = None):
    return _wrap(make_local_scores(
        model, "grand_last_layer", eval_mode=eval_mode,
        use_pallas=resolve_use_pallas(use_pallas)), mesh)


@functools.cache
def make_grand_step(model, mesh: Mesh | None = None, chunk: int = 32,
                    eval_mode: bool = True,
                    use_pallas: bool | None = None):
    """Full GraNd: per-example gradient norm over ALL parameters, the naive
    ``vmap(grad)`` way.

    Inside ``shard_map`` each device sees its local slice of the batch; the slice is
    reshaped to ``[n_chunks, chunk]`` and ``lax.map`` runs a ``vmap`` of single-example
    grads per chunk, reducing each gradient to its global norm immediately so at most
    ``chunk`` gradient pytrees are live per device.
    """
    return _wrap(make_local_scores(
        model, "grand_vmap", chunk=chunk, eval_mode=eval_mode,
        use_pallas=resolve_use_pallas(use_pallas)), mesh)


@functools.cache
def make_grand_batched_step(model, mesh: Mesh | None = None,
                            use_pallas: bool | None = None):
    """Full GraNd via the batched exact algorithm (``grand_batched.py``): one
    batched forward + one backward w.r.t. per-layer output perturbations, then
    closed-form per-layer norm contractions — no per-example backwards, so the
    MXU sees large batched matmuls instead of batch-1 convolutions. Eval-mode
    only (train-mode BatchNorm couples examples; see the module docstring).
    ``use_pallas`` selects the fused conv-grad-norm kernel for the large-S
    conv layers (None = auto: on for TPU backends). ``DDT_GRAND_FUSED=1``
    routes through ``batched_grand_scores_fused`` (contractions inside the
    backward pass) instead of the two-phase composition;
    ``DDT_GRAND_MEGAKERNEL=1`` additionally routes eligible convs through the
    layout-persistent backward+contraction megakernel
    (``pallas_kernels.conv_bwd_grad_norm_sq_pallas``)."""
    return _wrap(make_local_scores(
        model, "grand_batched",
        use_pallas=resolve_use_pallas(use_pallas)), mesh)


@functools.cache
def make_score_step(model, method: str, mesh: Mesh | None = None, chunk: int = 32,
                    eval_mode: bool = True, use_pallas: bool | None = None):
    """Factory keyed by config string (el2n | margin | grand | grand_vmap |
    grand_last_layer). ``grand`` runs the batched exact algorithm in eval mode
    and falls back to ``vmap(grad)`` for train-mode (reference-quirk) scoring;
    ``grand_vmap`` forces the naive path (cross-checking, exotic layers)."""
    if method == "el2n":
        return make_el2n_step(model, mesh, eval_mode=eval_mode,
                              use_pallas=use_pallas)
    if method == "margin":
        return make_margin_step(model, mesh, eval_mode=eval_mode)
    if method == "grand":
        if eval_mode:
            return make_grand_batched_step(model, mesh, use_pallas=use_pallas)
        return make_grand_step(model, mesh, chunk=chunk, eval_mode=eval_mode,
                               use_pallas=use_pallas)
    if method == "grand_vmap":
        return make_grand_step(model, mesh, chunk=chunk, eval_mode=eval_mode,
                               use_pallas=use_pallas)
    if method == "grand_last_layer":
        return make_grand_last_layer_step(model, mesh, eval_mode=eval_mode,
                                          use_pallas=use_pallas)
    raise ValueError(f"unknown score method {method!r}")


def resolve_score_method(method: str, eval_mode: bool) -> str:
    """The ``make_score_step`` dispatch rule as data: which local-scores
    method a config-string method actually runs (``grand`` is the batched
    exact algorithm in eval mode, ``vmap(grad)`` otherwise)."""
    if method == "grand":
        return "grand_batched" if eval_mode else "grand_vmap"
    return method


@functools.cache
def make_score_chunk(model, method: str, mesh: Mesh | None = None,
                     chunk: int = 32, eval_mode: bool = True,
                     use_pallas: bool | None = None):
    """K score batches compiled into ONE dispatch — the scoring twin of
    ``train/steps.make_train_chunk``.

    ``score_chunk(variables, images, labels, mask) -> scores [K, B]``: the
    operands are ``[K, B, ...]`` blocks of the PRE-BATCHED resident dataset
    (``ops/scoring.ScoreResident`` — batch composition identical to the host
    assembler's: dataset order, row-0 tail images, zeroed tail labels,
    mask 0), already laid out batch-dim-sharded over the flat mesh, and the
    scan consumes them as ``xs`` — each step reads its batch slice straight
    from the resident block, so the chunk needs no gather, no accumulator
    and no layout change anywhere: one dispatch runs K score batches and the
    stacked ``[K, B]`` output IS the score block, fetched once per seed.
    Scores are BIT-identical to the per-batch engine's
    (``tests/test_score_chunked.py`` pins it across the method registry).

    The scan is fully unrolled and a length-1 tail bypasses it, for the same
    compile-identity reasons as the train chunk (train/steps.py docstring).
    ``use_pallas`` None resolves like the step factories."""
    from ..obs import registry as obs_registry
    from ..obs import xla as obs_xla

    local = make_local_scores(model, resolve_score_method(method, eval_mode),
                              chunk=chunk, eval_mode=eval_mode,
                              use_pallas=resolve_use_pallas(use_pallas))
    if mesh is None or mesh.size == 1:
        scores_fn = local
    else:
        from ..parallel.mesh import flat_batch_spec
        spec = flat_batch_spec(mesh)
        scores_fn = _shard_map(local, mesh=mesh,
                               in_specs=(P(), spec, spec, spec),
                               out_specs=spec)

    def score_chunk(variables, images, labels, mask):
        def body(_, xs):
            img, lab, m = xs
            return 0, scores_fn(variables, img, lab, m)

        if images.shape[0] == 1:   # length-1 scan ≠ bare body bitwise
            _, s = body(0, (images[0], labels[0], mask[0]))
            return s[None]
        _, s = jax.lax.scan(body, 0, (images, labels, mask), unroll=True)
        return s

    # No donation: every operand (variables, resident blocks) is reused by
    # the next dispatch/seed; the chunk's output is freshly allocated.
    jitted = jax.jit(score_chunk)

    @functools.wraps(jitted)
    def dispatch(variables, images, labels, mask, **kwargs):
        # Host-side dispatch counter (train/steps._counted's pattern): the
        # chunked engine's whole point is fewer dispatches — count them.
        obs_registry.inc("dispatches_score_chunk")
        if obs_xla.current() is not None:
            # Once-per-geometry compiled-program harvest (cost/memory
            # analysis, compile wall) — [K, B] blocks score K*B examples.
            obs_xla.harvest("score_chunk", jitted,
                            (variables, images, labels, mask), kwargs,
                            images.shape[:2],
                            images.shape[0] * images.shape[1])
        return jitted(variables, images, labels, mask, **kwargs)

    # The underlying jitted function, exposed for AOT warming: the serving
    # engine's compiled-program cache calls ``dispatch.jitted.lower(...)
    # .compile()`` on a cache miss — jax's compilation cache is shared with
    # the dispatch path (pinned by PR-6's probe measurements), so the first
    # real dispatch after a warm never recompiles.
    dispatch.jitted = jitted
    return dispatch
