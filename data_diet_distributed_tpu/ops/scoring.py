"""Whole-dataset scoring driver: sharded pass + multi-seed averaging.

Replaces the reference's single-GPU serial scoring loop (``get_scores_and_prune.py:11-20``,
invoked on one device at ``ddp.py:56``) with a mesh-wide pass: every device scores its
shard of every batch, and scores land in a host array joined by global example index.
Multi-seed averaging (the paper scores with ~10 independently-trained checkpoints and
averages; the reference supports a single seed only) is a mean over per-seed passes that
reuses the same compiled step — one compilation, ``n_seeds`` executions.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import numpy as np

from ..data.datasets import ArrayDataset, make_position_joiner
from ..data.pipeline import BatchSharder, device_stream, iterate_batches
from .scores import make_score_step


def _to_host(batched: list[jax.Array]) -> list[np.ndarray]:
    """Fetch (possibly multi-host sharded) device arrays to every host — one
    call for the whole dataset pass, so device compute is never serialized
    against per-batch host transfers (dispatch stays fully async)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return [np.asarray(a) for a in
                multihost_utils.process_allgather(batched, tiled=True)]
    return [np.asarray(a) for a in jax.device_get(batched)]


# Keep the whole dataset device-resident across scoring seeds when it fits
# comfortably in HBM: up to 1 GiB per mesh device (batches are spread over the
# mesh), capped at 4 GiB total. CIFAR at fp32 (~0.6 GiB) qualifies on a single
# chip; ImageNet-scale npz sets stream.
_DEVICE_RESIDENT_PER_DEVICE_BYTES = 1 << 30
_DEVICE_RESIDENT_MAX_BYTES = 4 << 30


def score_dataset(model, variables_seeds: Sequence, ds: ArrayDataset, *,
                  method: str = "el2n", batch_size: int = 512,
                  sharder: BatchSharder | None = None, chunk: int = 32,
                  eval_mode: bool = True, use_pallas: bool | None = None,
                  score_step=None, device_resident: bool | None = None,
                  on_seed_done=None) -> np.ndarray:
    """Score every example; returns ``scores[N]`` aligned with ``ds`` row order.

    ``variables_seeds`` is a sequence of model variable pytrees (one per scoring seed);
    the returned score is the per-example mean over seeds. ``device_resident``
    (None = auto by dataset size) uploads the batches once and reuses them for
    every seed — multi-seed scoring then pays host→device transfer once, not
    ``n_seeds`` times.

    ``on_seed_done(k, seed_scores)`` fires after each seed's full pass with
    that seed's float64 score vector (every process holds it, multi-host
    included) — the stage-resume attachment point: ``compute_scores``
    persists per-seed partials there, so an interrupted multi-seed scoring
    run loses at most the in-flight seed's pass. The hook may raise (e.g.
    ``Preempted`` at a seed boundary); completed seeds' hooks have already
    run.
    """
    mesh = sharder.mesh if sharder is not None else None
    if sharder is not None and len(sharder.axes) < len(mesh.axis_names):
        # Scoring flattens the whole mesh (the score step shards batches over
        # every axis — ops/scores._wrap): re-sharder so host placement matches
        # the step's layout and batch sizes round to all-device divisibility.
        sharder = BatchSharder.flat(mesh)
    if mesh is not None and mesh.size > 1:
        # Re-replicate TP-sharded scoring params ONCE: the score step's
        # shard_map takes variables at P(), and leaving the resharding to jit
        # would all-gather the classifier on EVERY batch invocation.
        from ..parallel.mesh import replicate
        variables_seeds = [replicate(v, mesh) for v in variables_seeds]
    if score_step is None:
        score_step = make_score_step(model, method, mesh, chunk=chunk,
                                     eval_mode=eval_mode, use_pallas=use_pallas)
    if sharder is not None:
        batch_size = sharder.global_batch_size_for(batch_size)

    n = len(ds)
    total = np.zeros(n, np.float64)
    # Position-in-ds join for batch scores by global index; handles sparse
    # bring-your-own id spaces without an O(max_id) table.
    pos_of = make_position_joiner(ds.indices)

    if device_resident is None:
        # Batches shard over every flattened mesh axis, so the per-device
        # budget scales with the full device count.
        n_dev = sharder.mesh.size if sharder is not None else 1
        budget = min(n_dev * _DEVICE_RESIDENT_PER_DEVICE_BYTES,
                     _DEVICE_RESIDENT_MAX_BYTES)
        # Size the decision by the UPLOADED footprint (batches materialize as
        # float32 even when the dataset is lazy uint8/mmap on disk).
        device_resident = (len(variables_seeds) > 1
                           and ds.images.size * 4 <= budget)

    def device_batches():
        if sharder is not None:
            # Production path: per-process image assembly under multihost (the
            # global index/mask stay host-side for the score join below).
            for host_batch, batch in device_stream(ds, batch_size, sharder):
                yield (host_batch["index"], host_batch["mask"].astype(bool),
                       batch)
            return
        for host_batch in iterate_batches(ds, batch_size, shuffle=False):
            batch = {k: jax.numpy.asarray(v) for k, v in host_batch.items()}
            yield (host_batch["index"], host_batch["mask"].astype(bool), batch)

    resident = list(device_batches()) if device_resident else None
    # Streaming mode uploads batches as it dispatches; flushing on a bounded
    # window keeps peak HBM at ~window batches (a full-dataset flush would pin
    # every uploaded batch live — an OOM for >HBM datasets, the exact case
    # streaming exists for). Resident mode holds the dataset anyway: one flush.
    window = len(resident) if resident is not None else 8
    for k, variables in enumerate(variables_seeds):
        # Per-seed accumulator (not straight into ``total``): the completed
        # seed's vector is what on_seed_done persists for stage resume.
        seed_scores = np.zeros(n, np.float64)
        pending: list[tuple[np.ndarray, np.ndarray, jax.Array]] = []

        def flush():
            for (idx, mask, _), scores in zip(
                    pending, _to_host([p[2] for p in pending])):
                seed_scores[pos_of(idx[mask])] += scores[mask]
            pending.clear()

        for idx, mask, batch in (resident if resident is not None
                                 else device_batches()):
            pending.append((idx, mask, score_step(variables, batch)))
            if len(pending) >= window:
                flush()
        flush()
        total += seed_scores
        if on_seed_done is not None:
            on_seed_done(k, seed_scores)
    return (total / len(variables_seeds)).astype(np.float32)
