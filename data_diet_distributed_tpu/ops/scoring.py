"""Whole-dataset scoring driver: sharded pass + multi-seed averaging.

Replaces the reference's single-GPU serial scoring loop (``get_scores_and_prune.py:11-20``,
invoked on one device at ``ddp.py:56``) with a mesh-wide pass: every device scores its
shard of every batch, and scores land in a host array joined by global example index.
Multi-seed averaging (the paper scores with ~10 independently-trained checkpoints and
averages; the reference supports a single seed only) is a mean over per-seed passes that
reuses the same compiled step — one compilation, ``n_seeds`` executions.

Multi-process fetch engine: the default STREAM fetch DMAs only this rank's
score shards to host per flush (overlapped with the next window's dispatch)
and joins ranks with one sliced cross-process sum per seed — the full
``[N]`` vector never round-trips whole through every process per flush (the
legacy behavior, kept behind ``DDT_SCORE_FETCH=allgather`` and pinned
identical by the 2-process drill). Measured −71 % fetch wall on the
2-process CPU lane (PERFORMANCE.md "Pod-scale comm layer").
"""

from __future__ import annotations

import os
from collections.abc import Sequence

import jax
import numpy as np

from ..data import sharded
from ..data.datasets import ArrayDataset, make_position_joiner
from ..data.pipeline import (BatchSharder, PrefetchIterator, data_plane_record,
                             device_stream, iterate_batches, merge_stall_stats,
                             num_batches)
from ..obs import registry as obs_registry
from ..obs import scoreboard as obs_scoreboard
from .scores import make_score_chunk, make_score_step

#: Hard clamp on the score-chunk length (batches per dispatch): the chunk is
#: fully unrolled (compile size grows with K), and one chunk is the dispatch
#: granularity a host signal can interleave at. 32 b2048 GraNd batches per
#: dispatch covers the 50k north-star epoch in one dispatch per seed.
MAX_SCORE_CHUNK_STEPS = 32


def resolve_score_chunk_steps(chunk_steps: int | None, n_batches: int,
                              resident: bool) -> int:
    """The chunked-score-engine selection policy (1 = the per-batch path).

    ``None`` = auto: chunking on for single-process device-resident passes,
    sized to the WHOLE epoch (one dispatch per seed) up to the clamp; 0/1 =
    forced per-batch; K>1 = requested. Streaming passes and multi-host
    runtimes fall back to per-batch — the chunk scans the RESIDENT gather,
    and the scatter into the replicated accumulator assumes every device is
    fed by this process."""
    if chunk_steps is not None and chunk_steps <= 1:
        return 1
    if not resident or jax.process_count() > 1:
        return 1
    k = n_batches if chunk_steps is None else int(chunk_steps)
    return max(1, min(k, n_batches, MAX_SCORE_CHUNK_STEPS))


def _to_host(batched: list[jax.Array]) -> list[np.ndarray]:
    """Fetch device arrays to every host — one call for the whole flush
    window, so device compute is never serialized against per-batch host
    transfers (dispatch stays fully async).

    The collective (``process_allgather``) runs only when an array is
    actually NOT fully addressable from this process: fully-addressable
    arrays — every single-host run, multi-device included, and any
    mesh-local array under a multi-process runtime — take the plain
    ``jax.device_get``, which is a local DMA, not a collective. (The old
    guard keyed on ``process_count`` alone, which was correct by accident
    for the single-host case; addressability is the property that actually
    decides.)"""
    if (jax.process_count() > 1
            and not all(a.is_fully_addressable for a in batched)):
        from jax.experimental import multihost_utils
        return [np.asarray(a) for a in
                multihost_utils.process_allgather(batched, tiled=True)]
    return [np.asarray(a) for a in jax.device_get(batched)]


def resolve_fetch_mode() -> str:
    """The multi-process score-fetch engine: ``"stream"`` (default — each
    rank fetches only its local shards, one cross-process sum per seed) or
    ``"allgather"`` (the legacy full-``[N]``-on-every-rank per-flush
    collective), from ``DDT_SCORE_FETCH``. The two are pinned identical by
    the 2-process drill; the env knob exists for that A/B and as the
    rollback lever."""
    mode = os.environ.get("DDT_SCORE_FETCH", "stream").lower()
    return "allgather" if mode == "allgather" else "stream"


def _local_shard_rows(arr: jax.Array) -> list[tuple[slice, np.ndarray]]:
    """This process's OWNED row-slices of a 1-D batch-sharded score array,
    as ``(global_rows, host_data)`` pairs — a rank-local device→host DMA,
    no collective anywhere. Ownership = ``replica_id == 0``: for a sharded
    array every addressable shard owns its rows; for a (degenerate)
    replicated array exactly one replica owns each row globally, so the
    per-seed cross-process sum can never double-count."""
    out = []
    n = arr.shape[0]
    for shard in arr.addressable_shards:
        if shard.replica_id != 0:
            continue
        rows = shard.index[0] if shard.index else slice(None)
        rows = slice(rows.start or 0, n if rows.stop is None else rows.stop)
        out.append((rows, np.asarray(shard.data)))
    return out


#: Elements per cross-process combine slice: bounds the [world, slice] host
#: buffer the per-seed sum materializes (1M f64 x world ranks ≈ 8 MB/rank
#: per slice — a 1.2M-score pod pass streams in two slices).
_COMBINE_SLICE_ELEMS = 1 << 20


def _sum_across_processes(vec: np.ndarray) -> np.ndarray:
    """Sum per-rank partial score vectors into the full ``[N]`` on every
    rank — ONE sliced collective per seed (vs the legacy path's full-vector
    allgather per FLUSH). Each position is owned by exactly one rank
    (``_local_shard_rows``), so the sum adds a value to zeros — bit-exact
    regardless of rank order."""
    if jax.process_count() <= 1:
        return vec
    from jax.experimental import multihost_utils
    out = np.empty_like(vec)
    for s in range(0, len(vec), _COMBINE_SLICE_ELEMS):
        e = min(s + _COMBINE_SLICE_ELEMS, len(vec))
        out[s:e] = np.asarray(
            multihost_utils.process_allgather(
                np.ascontiguousarray(vec[s:e]))).sum(axis=0)
    return out


# Keep the whole dataset device-resident across scoring seeds when it fits
# comfortably in HBM: up to 1 GiB per mesh device (batches are spread over the
# mesh), capped at 4 GiB total. CIFAR at fp32 (~0.6 GiB) qualifies on a single
# chip; ImageNet-scale npz sets stream.
_DEVICE_RESIDENT_PER_DEVICE_BYTES = 1 << 30
_DEVICE_RESIDENT_MAX_BYTES = 4 << 30


def fits_residency(ds: ArrayDataset, n_devices: int) -> bool:
    """Whether the dataset's UPLOADED footprint (batches materialize as
    float32 even when the dataset is lazy uint8/mmap on disk) fits the
    device-residency budget — THE predicate ``score_dataset``'s auto rule
    uses, public so ``bench.py`` predicts the same engine selection it
    reports."""
    budget = min(n_devices * _DEVICE_RESIDENT_PER_DEVICE_BYTES,
                 _DEVICE_RESIDENT_MAX_BYTES)
    return ds.images.size * 4 <= budget


def score_dataset(model, variables_seeds: Sequence, ds: ArrayDataset, *,
                  method: str = "el2n", batch_size: int = 512,
                  sharder: BatchSharder | None = None, chunk: int = 32,
                  eval_mode: bool = True, use_pallas: bool | None = None,
                  score_step=None, device_resident: bool | None = None,
                  chunk_steps: int | None = None,
                  on_seed_done=None, seed_ids: Sequence[int] | None = None,
                  data_plane: str = "auto", prefetch_depth: int = 2,
                  logger=None) -> np.ndarray:
    """Score every example; returns ``scores[N]`` aligned with ``ds`` row order.

    ``variables_seeds`` is a sequence of model variable pytrees (one per scoring seed);
    the returned score is the per-example mean over seeds. ``device_resident``
    (None = auto by dataset size) uploads the batches once and reuses them for
    every seed — multi-seed scoring then pays host→device transfer once, not
    ``n_seeds`` times.

    ``chunk_steps`` arms the CHUNKED score engine on the resident path
    (None = auto: the whole epoch per dispatch, clamped; 0/1 = per-batch):
    the dataset uploads once as pre-batched pre-sharded blocks
    (``ScoreResident``) and K score batches compile into one dispatch whose
    scan reads each batch straight from the block
    (``ops/scores.make_score_chunk``) — a full score epoch becomes ONE
    dispatch per seed instead of N/B relay round-trips, with bit-identical
    scores (``resolve_score_chunk_steps`` documents the streaming/multi-host
    fallbacks; a caller-supplied ``score_step`` also forces per-batch, since
    the chunk compiles its own program).

    ``on_seed_done(k, seed_scores)`` fires after each seed's full pass with
    that seed's float64 score vector (every process holds it, multi-host
    included) — the stage-resume attachment point: ``compute_scores``
    persists per-seed partials there, so an interrupted multi-seed scoring
    run loses at most the in-flight seed's pass. The hook may raise (e.g.
    ``Preempted`` at a seed boundary); completed seeds' hooks have already
    run.

    Every completed seed pass also feeds the Score Observatory
    (``obs/scoreboard.py``, no-op until installed): one ``score_stats``
    record per (method, seed) from the just-fetched host array.
    ``seed_ids`` labels the passes with the caller's true seed values
    (``compute_scores`` passes its seed list); the pass index is the label
    otherwise.

    ``data_plane`` selects the feed engine (``data.data_plane``): ``"auto"``
    keeps the size-based residency rule above; ``"resident"`` forces the
    upload-once path regardless of size; ``"streaming"`` forbids residency
    and, single-process, runs the chunked engine over ``ScoreStream``
    blocks — assembled ``prefetch_depth`` blocks ahead and bit-identical to
    the resident pass — so >HBM (and >host-RAM, via the sharded format's
    bounded cache) datasets score under a fixed memory budget. A streaming
    pass logs one ``data_plane`` record through ``logger`` when given.
    """
    mesh = sharder.mesh if sharder is not None else None
    if sharder is not None and len(sharder.axes) < len(mesh.axis_names):
        # Scoring flattens the whole mesh (the score step shards batches over
        # every axis — ops/scores._wrap): re-sharder so host placement matches
        # the step's layout and batch sizes round to all-device divisibility.
        sharder = BatchSharder.flat(mesh)
    if mesh is not None and mesh.size > 1:
        # Re-replicate TP-sharded scoring params ONCE: the score step's
        # shard_map takes variables at P(), and leaving the resharding to jit
        # would all-gather the classifier on EVERY batch invocation.
        from ..parallel.mesh import replicate
        variables_seeds = [replicate(v, mesh) for v in variables_seeds]
    caller_step = score_step is not None
    if score_step is None:
        score_step = make_score_step(model, method, mesh, chunk=chunk,
                                     eval_mode=eval_mode, use_pallas=use_pallas)
    if sharder is not None:
        batch_size = sharder.global_batch_size_for(batch_size)

    n = len(ds)
    total = np.zeros(n, np.float64)
    # Position-in-ds join for batch scores by global index; handles sparse
    # bring-your-own id spaces without an O(max_id) table.
    pos_of = make_position_joiner(ds.indices)

    if data_plane == "streaming":
        # Streaming plane: never hold the dataset on device (or host — the
        # chunked engine below feeds from ScoreStream, whose blocks flow
        # through the bounded host cache for sharded datasets).
        device_resident = False
    elif data_plane == "resident" and device_resident is None:
        device_resident = True
    if device_resident is None:
        # Batches shard over every flattened mesh axis, so the per-device
        # budget scales with the full device count.
        n_dev = sharder.mesh.size if sharder is not None else 1
        device_resident = ((len(variables_seeds) > 1 or chunk_steps)
                           and fits_residency(ds, n_dev))

    if not caller_step:
        # The streaming plane is chunk-capable single-process: ScoreStream
        # satisfies the block contract resolve_score_chunk_steps gates on.
        stream_chunks = data_plane == "streaming" and jax.process_count() == 1
        k_chunk = resolve_score_chunk_steps(
            chunk_steps, num_batches(n, batch_size),
            bool(device_resident) or stream_chunks)
        if k_chunk > 1:
            return _score_dataset_chunked(
                model, variables_seeds, ds, method=method,
                batch_size=batch_size, sharder=sharder, chunk=chunk,
                eval_mode=eval_mode, use_pallas=use_pallas, k_chunk=k_chunk,
                on_seed_done=on_seed_done, seed_ids=seed_ids,
                streaming=stream_chunks and not device_resident,
                prefetch_depth=prefetch_depth, logger=logger)

    def device_batches():
        if sharder is not None:
            # Production path: per-process image assembly under multihost (the
            # global index/mask stay host-side for the score join below).
            for host_batch, batch in device_stream(ds, batch_size, sharder):
                yield (host_batch["index"], host_batch["mask"].astype(bool),
                       batch)
            return
        for host_batch in iterate_batches(ds, batch_size, shuffle=False):
            batch = {k: jax.numpy.asarray(v) for k, v in host_batch.items()}
            yield (host_batch["index"], host_batch["mask"].astype(bool), batch)

    resident = list(device_batches()) if device_resident else None
    # Streaming mode uploads batches as it dispatches; flushing on a bounded
    # window keeps peak HBM at ~window batches (a full-dataset flush would pin
    # every uploaded batch live — an OOM for >HBM datasets, the exact case
    # streaming exists for). Resident mode holds the dataset anyway: one flush.
    window = len(resident) if resident is not None else 8
    # Multi-process fetch engine: STREAM (default) fetches only this rank's
    # shards per flush — a local DMA overlapped with the next window's
    # dispatch — and joins ranks with ONE sliced sum per seed, so the [N]
    # score vector never round-trips whole through every process per flush.
    # DDT_SCORE_FETCH=allgather keeps the legacy per-flush collective
    # (pinned identical by the 2-process drill). Gated on a sharder: the
    # per-rank ownership invariant (replica_id 0 covers each row once
    # globally) holds only for globally-SHARDED score arrays — a
    # sharder-less multi-process call scores per-process LOCAL arrays where
    # every rank owns everything, and streaming them would world-x
    # double-count at the seed join (those arrays are fully addressable, so
    # the legacy branch below is already collective-free for them).
    stream = (jax.process_count() > 1 and sharder is not None
              and resolve_fetch_mode() == "stream")
    for k, variables in enumerate(variables_seeds):
        # Per-seed accumulator (not straight into ``total``): the completed
        # seed's vector is what on_seed_done persists for stage resume.
        seed_scores = np.zeros(n, np.float64)
        pending: list[tuple[np.ndarray, np.ndarray, jax.Array]] = []

        def flush():
            with obs_registry.timed("score_fetch_s"):
                if stream:
                    for idx, mask, arr in pending:
                        for rows, data in _local_shard_rows(arr):
                            m = mask[rows]
                            seed_scores[pos_of(idx[rows][m])] += data[m]
                else:
                    for (idx, mask, _), scores in zip(
                            pending, _to_host([p[2] for p in pending])):
                        seed_scores[pos_of(idx[mask])] += scores[mask]
            pending.clear()

        for idx, mask, batch in (resident if resident is not None
                                 else device_batches()):
            pending.append((idx, mask, score_step(variables, batch)))
            if len(pending) >= window:
                flush()
        flush()
        if stream:
            # The seed-boundary rank join: every process ends the pass with
            # the full [N] float64 vector — the contract stage-resume
            # partials and the scoreboard rely on — via one sliced sum.
            with obs_registry.timed("score_fetch_s"):
                seed_scores = _sum_across_processes(seed_scores)
        total += seed_scores
        # Observatory note BEFORE the caller hook: on_seed_done may raise
        # (seed-boundary Preempted) and the completed pass's stats belong in
        # the stream either way.
        obs_scoreboard.note_seed_scores(
            method, seed_ids[k] if seed_ids is not None else k, seed_scores)
        if on_seed_done is not None:
            on_seed_done(k, seed_scores)
    return (total / len(variables_seeds)).astype(np.float32)


def _dispatch_score_chunk(chunk_fn, variables, images, labels, mask):
    """One chunked score dispatch: K batches, one host round trip to enqueue.
    A module-level seam (the ``train/loop._dispatch_chunk`` pattern) so tests
    can count and interpose at chunk boundaries."""
    return chunk_fn(variables, images, labels, mask)


class ScoreResident:
    """Pre-batched resident dataset for the chunked score engine.

    ``images``/``labels``/``mask`` are ``[nb, B, ...]`` device arrays whose
    batch composition matches the host assembler's EXACTLY (dataset order;
    the tail batch padded with row-0 images, zeroed labels, mask 0 — row-0
    image padding matters for the train-mode-BN reference quirk, where tail
    content feeds the real rows' batch statistics), laid out with the batch
    dim sharded over the flat mesh — the same layout the score step's
    shard_map consumes, so the chunk's scan reads each batch straight out of
    the block with no gather and no resharding anywhere."""

    def __init__(self, ds: ArrayDataset, batch_size: int, mesh=None):
        dense = ds.dense()   # lazy (mmap) datasets materialize normalized rows
        self.n = len(ds)
        self.nb = num_batches(self.n, batch_size)
        self.batch_size = batch_size
        pad = self.nb * batch_size - self.n
        imgs = np.asarray(dense.images, np.float32)
        if pad:
            imgs = np.concatenate(
                [imgs, np.broadcast_to(imgs[0], (pad, *imgs.shape[1:]))])
        labels = np.zeros(self.nb * batch_size, np.int32)
        labels[:self.n] = dense.labels
        mask = np.zeros(self.nb * batch_size, np.float32)
        mask[:self.n] = 1.0
        # The block layout (leading batch-index dim unsharded, batch dim over
        # the flat mesh) — kept public so the serving engine can place its
        # per-request [1, B, ...] blocks EXACTLY like the resident blocks.
        self.sharding = None
        if mesh is not None and mesh.size > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.sharding = NamedSharding(mesh,
                                          P(None, tuple(mesh.axis_names)))
            sharding = self.sharding

            def put(a):
                return jax.device_put(a, sharding)
        else:
            put = jax.device_put
        self.images = put(np.ascontiguousarray(
            imgs.reshape(self.nb, batch_size, *imgs.shape[1:])))
        self.labels = put(labels.reshape(self.nb, batch_size))
        self.mask = put(mask.reshape(self.nb, batch_size))

    def blocks(self, k_chunk: int):
        """``(images, labels, mask)`` operand triples of ``<= k_chunk``
        batches each. The whole-epoch block (the auto default) is the
        resident arrays THEMSELVES — no copy; clamped multi-chunk passes
        slice (one contiguous device copy per block)."""
        for s in range(0, self.nb, k_chunk):
            e = min(s + k_chunk, self.nb)
            if s == 0 and e == self.nb:
                yield self.images, self.labels, self.mask
            else:
                yield self.images[s:e], self.labels[s:e], self.mask[s:e]


class ScoreStream:
    """Streaming twin of ``ScoreResident`` for datasets that must not be
    materialized: same ``(images, labels, mask)`` block layout, composition
    and sharding, but each block is assembled from the host dataset by the
    prefetch thread (``data/pipeline.PrefetchIterator``) and uploaded
    just-in-time — peak footprint is ~``prefetch_depth + 1`` blocks of
    ``k_chunk`` batches, host and device, instead of the whole dataset.
    Blocks come from the SAME host assembler as the per-batch path
    (``iterate_batches``: dataset order, tail padded with row-0 images,
    zeroed labels, mask 0), so scores are bit-identical to the resident
    engine. Re-assembles per seed (multi-seed passes pay host traffic
    ``n_seeds`` times — the cost of not holding the dataset anywhere).
    Single-process only, like the chunked engine it feeds."""

    def __init__(self, ds: ArrayDataset, batch_size: int, mesh=None, *,
                 prefetch_depth: int = 2):
        if jax.process_count() > 1:
            raise ValueError("ScoreStream is single-process only")
        self.ds = ds
        self.n = len(ds)
        self.nb = num_batches(self.n, batch_size)
        self.batch_size = batch_size
        self.prefetch_depth = prefetch_depth
        #: Cumulative prefetch stall accounting over every ``blocks()`` pass
        #: (one per seed) — the scoring ``data_plane`` record's payload.
        self.stall_stats: dict = {}
        self.sharding = None
        if mesh is not None and mesh.size > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self.sharding = NamedSharding(mesh,
                                          P(None, tuple(mesh.axis_names)))

    def _block(self, pend: list[dict]):
        put = (jax.device_put if self.sharding is None
               else lambda a: jax.device_put(a, self.sharding))
        images = np.stack([np.asarray(hb["image"], np.float32)
                           for hb in pend])
        labels = np.stack([np.ascontiguousarray(hb["label"], np.int32)
                           for hb in pend])
        mask = np.stack([np.asarray(hb["mask"], np.float32) for hb in pend])
        return put(images), put(labels), put(mask)

    def blocks(self, k_chunk: int):
        """Prefetched ``(images, labels, mask)`` triples of ``<= k_chunk``
        batches each — the ``ScoreResident.blocks`` contract, with assembly
        and upload running ``prefetch_depth`` blocks ahead of dispatch."""
        def produce():
            pend: list[dict] = []
            for hb in iterate_batches(self.ds, self.batch_size,
                                      shuffle=False):
                pend.append(hb)
                if len(pend) == k_chunk:
                    yield self._block(pend)
                    pend = []
            if pend:
                yield self._block(pend)

        it = PrefetchIterator(produce(), depth=self.prefetch_depth,
                              stage="score")
        try:
            yield from it
        finally:
            it.close()
            merge_stall_stats(self.stall_stats, it.stats())


def score_resident_pass(chunk_fn, resident: "ScoreResident", variables,
                        k_chunk: int) -> np.ndarray:
    """ONE seed's whole scoring pass over a block feed (``ScoreResident``,
    or its streaming twin ``ScoreStream`` — same ``blocks()`` contract):
    ``ceil(nb / K)`` chunked dispatches and ONE fetch of the stacked score
    blocks — the epoch's entire device→host traffic. Returns the float64
    ``[n]`` seed vector (float64 exactly represents every float32, so a
    resumed-partial mean stays bit-identical). The one definition shared by
    ``_score_dataset_chunked`` and the serving engine's warm resident path
    (``serve/engine.py``), so the two cannot drift."""
    outs = [_dispatch_score_chunk(chunk_fn, variables, *blk)
            for blk in resident.blocks(k_chunk)]
    with obs_registry.timed("score_fetch_s"):
        return np.concatenate(
            [np.asarray(o, np.float64) for o in jax.device_get(outs)],
            axis=0).reshape(-1)[:resident.n]


def _score_dataset_chunked(model, variables_seeds: Sequence, ds: ArrayDataset,
                           *, method: str, batch_size: int,
                           sharder: BatchSharder | None, chunk: int,
                           eval_mode: bool, use_pallas: bool | None,
                           k_chunk: int, on_seed_done=None,
                           seed_ids: Sequence[int] | None = None,
                           streaming: bool = False, prefetch_depth: int = 2,
                           logger=None) -> np.ndarray:
    """The chunked score epoch: each seed's pass is ``ceil(nb / K)`` chunked
    dispatches — one, on the default auto sizing — and ONE fetch of the
    stacked score blocks. The block feed is either the dataset uploaded ONCE
    as pre-batched pre-sharded blocks (``ScoreResident``) or, under
    ``streaming``, prefetch-assembled just-in-time blocks (``ScoreStream``,
    bit-identical composition, bounded footprint). Single-process only
    (``resolve_score_chunk_steps`` gates)."""
    mesh = sharder.mesh if sharder is not None else None
    multi = mesh is not None and mesh.size > 1
    resident = (ScoreStream(ds, batch_size, mesh,
                            prefetch_depth=prefetch_depth) if streaming
                else ScoreResident(ds, batch_size, mesh))
    chunk_fn = make_score_chunk(model, method, mesh if multi else None,
                                chunk=chunk, eval_mode=eval_mode,
                                use_pallas=use_pallas)
    total = np.zeros(resident.n, np.float64)
    fault: str | None = None
    try:
        for k, variables in enumerate(variables_seeds):
            seed_scores = score_resident_pass(chunk_fn, resident, variables,
                                              k_chunk)
            total += seed_scores
            obs_scoreboard.note_seed_scores(
                method, seed_ids[k] if seed_ids is not None else k,
                seed_scores)
            if on_seed_done is not None:
                on_seed_done(k, seed_scores)
    except BaseException as err:   # noqa: BLE001 — recorded, then re-raised
        fault = f"{type(err).__name__}: {err}"[:300]
        raise
    finally:
        # Emitted from finally so an aborted score pass (shard quarantine,
        # preemption) still reports its stall/fault stats — and any pending
        # data_fault/shard_quarantine records drain with it.
        if streaming and logger is not None:
            for rec in sharded.drain_fault_records():
                logger.log(rec.pop("kind"), **rec)
            record = data_plane_record("score", "chunked_stream",
                                       resident.stall_stats, ds)
            record["fault"] = fault
            logger.log("data_plane", **record)
    return (total / len(variables_seeds)).astype(np.float32)
