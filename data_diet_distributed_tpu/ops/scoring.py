"""Whole-dataset scoring driver: sharded pass + multi-seed averaging.

Replaces the reference's single-GPU serial scoring loop (``get_scores_and_prune.py:11-20``,
invoked on one device at ``ddp.py:56``) with a mesh-wide pass: every device scores its
shard of every batch, and scores land in a host array joined by global example index.
Multi-seed averaging (the paper scores with ~10 independently-trained checkpoints and
averages; the reference supports a single seed only) is a mean over per-seed passes that
reuses the same compiled step — one compilation, ``n_seeds`` executions.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import numpy as np

from ..data.datasets import ArrayDataset
from ..data.pipeline import BatchSharder, iterate_batches
from .scores import make_score_step


def _to_host(x: jax.Array) -> np.ndarray:
    """Fetch a (possibly multi-host sharded) device array to every host."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def score_dataset(model, variables_seeds: Sequence, ds: ArrayDataset, *,
                  method: str = "el2n", batch_size: int = 512,
                  sharder: BatchSharder | None = None, chunk: int = 32,
                  eval_mode: bool = True, use_pallas: bool | None = False,
                  score_step=None) -> np.ndarray:
    """Score every example; returns ``scores[N]`` aligned with ``ds`` row order.

    ``variables_seeds`` is a sequence of model variable pytrees (one per scoring seed);
    the returned score is the per-example mean over seeds.
    """
    mesh = sharder.mesh if sharder is not None else None
    if score_step is None:
        score_step = make_score_step(model, method, mesh, chunk=chunk,
                                     eval_mode=eval_mode, use_pallas=use_pallas)
    if sharder is not None:
        batch_size = sharder.global_batch_size_for(batch_size)

    n = len(ds)
    total = np.zeros(n, np.float64)
    # Position-in-ds lookup for joining batch scores back by global index.
    pos_of = np.full(int(ds.indices.max()) + 1, -1, np.int64)
    pos_of[ds.indices] = np.arange(n)

    for variables in variables_seeds:
        for host_batch in iterate_batches(ds, batch_size, shuffle=False):
            idx = host_batch["index"]
            mask = host_batch["mask"].astype(bool)
            batch = sharder(host_batch) if sharder is not None else {
                k: jax.numpy.asarray(v) for k, v in host_batch.items()}
            scores = _to_host(score_step(variables, batch))
            total[pos_of[idx[mask]]] += scores[mask]
    return (total / len(variables_seeds)).astype(np.float32)
