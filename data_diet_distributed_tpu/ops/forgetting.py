"""Forgetting-events score (Toneva et al. 2019, "An Empirical Study of Example
Forgetting during Deep Neural Network Learning").

A forgetting event for example ``i`` is a transition from classified-correctly
at one observation to misclassified at the next; examples with FEW events
("unforgettable") are the ones that can be dropped with least damage, so the
event count works directly as a keep-hardest pruning score. Examples that are
never learned rank strictly hardest (the paper treats them as forgotten
infinitely often).

The reference implements EL2N only (``get_scores_and_prune.py:15-18``); the
Data Diet paper uses forgetting scores as its main prior-work comparison, which
makes this the natural third scoring method for the framework. The accumulation
is host-side numpy over one ``[N]`` correctness vector per epoch — the device
work is the sharded correctness pass (``ops/scores.make_correctness_step``),
and N is dataset-sized (50k for CIFAR), so the host arithmetic is free.
"""

from __future__ import annotations

import numpy as np


class ForgettingTracker:
    """Accumulates forgetting events from one correctness vector per epoch.

    ``update`` is called once per observation (epoch) with ``correct[N]`` in
    dataset row order; ``scores`` returns the per-example event counts with
    never-learned examples pinned above every possible count.
    """

    def __init__(self, n: int):
        self.counts = np.zeros(n, np.int64)
        self.prev = np.zeros(n, bool)
        self.learned = np.zeros(n, bool)
        self.updates = 0

    def update(self, correct: np.ndarray) -> None:
        correct = np.asarray(correct, dtype=bool)
        if correct.shape != self.prev.shape:
            raise ValueError(
                f"correctness vector has shape {correct.shape}, expected "
                f"{self.prev.shape}")
        self.counts += self.prev & ~correct
        self.learned |= correct
        self.prev = correct
        self.updates += 1

    def scores(self) -> np.ndarray:
        """[N] float32 — event counts; never-learned = ``updates + 1`` (strictly
        above any achievable count, so keep-hardest retains them first)."""
        out = self.counts.astype(np.float32)
        out[~self.learned] = float(self.updates + 1)
        return out


class AUMTracker:
    """Average probability margin across the training trajectory — the
    area-under-the-margin identification score (Pleiss et al. 2020, "Identifying
    Mislabeled Data using the Area Under the Margin Ranking"), accumulated from
    the same per-epoch observations as ``ForgettingTracker``.

    Sign convention matches the framework's one-checkpoint ``margin`` method
    (``ops/scores.margin_from_logits``): each observation is
    ``max_{k≠y} p_k − p_y``, so HIGHER average = harder/likely-mislabeled and
    keep-hardest pruning composes unchanged. (The paper's logit-margin AUM is
    this quantity's sign-flip in logit space; the probability form keeps one
    margin definition across the framework.)
    """

    def __init__(self, n: int):
        self.total = np.zeros(n, np.float64)
        self.updates = 0

    def update(self, margin: np.ndarray) -> None:
        margin = np.asarray(margin, np.float64)
        if margin.shape != self.total.shape:
            raise ValueError(
                f"margin vector has shape {margin.shape}, expected "
                f"{self.total.shape}")
        self.total += margin
        self.updates += 1

    def scores(self) -> np.ndarray:
        """[N] float32 — mean margin over the observed epochs."""
        return (self.total / max(1, self.updates)).astype(np.float32)
