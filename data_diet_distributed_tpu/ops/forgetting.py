"""Forgetting-events score (Toneva et al. 2019, "An Empirical Study of Example
Forgetting during Deep Neural Network Learning").

A forgetting event for example ``i`` is a transition from classified-correctly
at one observation to misclassified at the next; examples with FEW events
("unforgettable") are the ones that can be dropped with least damage, so the
event count works directly as a keep-hardest pruning score. Examples that are
never learned rank strictly hardest (the paper treats them as forgotten
infinitely often).

The reference implements EL2N only (``get_scores_and_prune.py:15-18``); the
Data Diet paper uses forgetting scores as its main prior-work comparison, which
makes this the natural third scoring method for the framework. The accumulation
is host-side numpy over one ``[N]`` correctness vector per epoch — the device
work is the sharded correctness pass (``ops/scores.make_correctness_step``),
and N is dataset-sized (50k for CIFAR), so the host arithmetic is free.
"""

from __future__ import annotations

import numpy as np


class ForgettingTracker:
    """Accumulates forgetting events from one correctness vector per epoch.

    ``update`` is called once per observation (epoch) with ``correct[N]`` in
    dataset row order; ``scores`` returns the per-example event counts with
    never-learned examples pinned above every possible count.
    """

    def __init__(self, n: int):
        self.counts = np.zeros(n, np.int64)
        self.prev = np.zeros(n, bool)
        self.learned = np.zeros(n, bool)
        self.updates = 0

    def update(self, correct: np.ndarray) -> None:
        correct = np.asarray(correct, dtype=bool)
        if correct.shape != self.prev.shape:
            raise ValueError(
                f"correctness vector has shape {correct.shape}, expected "
                f"{self.prev.shape}")
        self.counts += self.prev & ~correct
        self.learned |= correct
        self.prev = correct
        self.updates += 1

    def scores(self) -> np.ndarray:
        """[N] float32 — event counts; never-learned = ``updates + 1`` (strictly
        above any achievable count, so keep-hardest retains them first)."""
        out = self.counts.astype(np.float32)
        out[~self.learned] = float(self.updates + 1)
        return out
