"""Pallas TPU kernels for the scoring hot path.

Eight fused kernels (see /opt/skills/guides/pallas_guide.md for the API conventions):

* ``el2n_pallas`` — fused ``softmax -> subtract one-hot -> row L2 norm -> mask``
  over logits. One VMEM round-trip instead of four HBM-materialized intermediates.
* ``grand_last_layer_pallas`` — the closed-form last-layer GraNd
  (``‖p − y‖ · sqrt(‖h‖² + 1)``) fused WITH the classifier matmul: features hit the
  MXU against the classifier weights and the score math runs on the VPU before
  logits ever leave VMEM. The model's own Dense head output goes unused and is
  dead-code-eliminated under jit, so the classifier matmul happens exactly once.
* ``conv_grad_norm_sq_pallas`` (v1) — the batched-GraNd conv hot loop
  (``grand_batched.py``): per-example Frobenius norm² of the conv weight
  gradient ``P_iᵀ G_i`` WITHOUT materializing the im2col patches or the [F, K]
  gradient in HBM. Key identity: writing ``M_o = Σ_s x_i[s·stride + o] g_i[s]``
  for each kernel offset ``o``, the full norm decomposes as
  ``‖∂W‖² = Σ_o ‖M_o‖²`` — each ``M_o`` is one small [C, K] MXU contraction over
  output positions, accumulated and squared entirely in VMEM. Takes pre-padded
  x; strided convs decompose into ``stride²`` unit-stride phase sub-problems
  (each offset belongs to exactly one phase; Mosaic rejects strided 4D slices).
* ``conv_grad_norm_sq_v2`` — same quantity for unit-stride 128-multiple-channel
  layers from RAW unpadded x: the kernel stages x itself by manual DMA into a
  zero-bordered VMEM buffer (virtual padding — no XLA pad, no layout copy per
  layer) and fuses the bias-gradient term.
* ``conv_grad_norm_sq_gram`` — the Gram form ``Σ(PPᵀ∘GGᵀ)`` for small-S
  wide-channel layers (stage 4), patches built IN VMEM via aligned scratch
  stores; the tiny grams never touch HBM. Shares the v2 staging helpers.
* ``_conv_norm_catdot_kernel`` (dispatched inside ``conv_grad_norm_sq_pallas``)
  — the cross-product "cat-dot" form for 128-aligned deep-contraction layers:
  one ``[kh·C, kw·K]`` dot computes every kernel-offset's weight-grad block at
  once with zero wasted FLOPs (see its docstring for the identity).
* ``bn_grad_norm_sq_pallas`` — eval-mode BatchNorm per-example grad-norm² in
  one VMEM pass, with same-shape layers stackable into a single launch.
* ``conv_bwd_grad_norm_sq_pallas`` — the layout-persistent MEGAKERNEL
  (``DDT_GRAND_MEGAKERNEL``): the layer's input-cotangent backward AND the
  weight-grad-norm contraction in ONE launch per layer, sharing the cotangent
  tile while it is VMEM-resident. The round-5 profile attributed ~26 ms of a
  74.9 ms batch-1024 pass to kernel-boundary composition (layout transitions
  into/out of each per-layer custom call — proven NOT to be graph structure
  by the fused-``custom_vjp`` parity result); fusing the two consumers of
  ``g`` removes one full boundary per conv layer. Within the same kernel,
  stage-1's 64-channel contractions are example-PACKED into full 128-lane
  tiles (two examples lane-concatenated per dot: 2× the FLOPs at 4× the MXU
  fill — rejected as a standalone kernel in round 3 when the pack cost showed
  up as extra boundaries, revisited here where it is free).

All kernels tile the batch dimension (fp32-aligned tiles) and keep channel
dimensions whole (Mosaic pads the lane dimension internally). Padded batch rows
carry ``mask == 0`` and score 0. On non-TPU backends the kernels run in
interpreter mode, so every test exercises the same code path CI runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax <= 0.4.x names it TPUCompilerParams; same constructor surface for
    # the fields used here (vmem_limit_bytes, has_side_effects).
    pltpu.CompilerParams = pltpu.TPUCompilerParams

TILE_B = 256  # batch rows per grid step; multiple of the fp32 sublane tile (8)


def _auto_interpret(interpret: bool | None) -> bool:
    return jax.default_backend() != "tpu" if interpret is None else interpret


def _tile_for(batch: int) -> int:
    """Largest fp32-sublane-aligned tile <= TILE_B covering the batch."""
    rounded = (batch + 7) // 8 * 8
    return min(TILE_B, rounded)


def _pad_batch(arrs, batch: int, tile: int):
    pad = (-batch) % tile
    if pad == 0:
        return arrs, batch + pad
    return [jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)) for a in arrs], batch + pad


def _onehot_err(logits, labels_col):
    """softmax(logits) − onehot(labels): the shared EL2N/GraNd error vector."""
    probs = jax.nn.softmax(logits, axis=-1)
    cols = jax.lax.broadcasted_iota(jnp.int32, probs.shape, 1)
    return probs - (cols == labels_col).astype(jnp.float32)


def _el2n_kernel(logits_ref, labels_ref, mask_ref, out_ref):
    err = _onehot_err(logits_ref[:], labels_ref[:])
    out_ref[:] = jnp.sqrt(jnp.sum(err * err, axis=-1, keepdims=True)) * mask_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def el2n_pallas(logits: jax.Array, labels: jax.Array, mask: jax.Array,
                interpret: bool | None = None) -> jax.Array:
    """EL2N scores [B] from logits [B, C]; fused single-pass kernel."""
    b, c = logits.shape
    tile = _tile_for(b)
    (logits, labels2, mask2), b_pad = _pad_batch(
        [logits.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
         mask.astype(jnp.float32)[:, None]], b, tile)
    out = pl.pallas_call(
        _el2n_kernel,
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(logits, labels2, mask2)
    return out[:b, 0]


# --------------------------------------------------------------------------
# Fused conv weight-grad-norm kernel (the batched-GraNd hot loop).
# --------------------------------------------------------------------------

# Per-grid-step working-set budget for the BlockSpec conv kernels. The 16 MiB
# scoped-VMEM default is a COMPILER knob (v5e compiles and runs these kernels
# with far higher limits — verified on-chip); wide-channel layers (WideResNet's
# 160/320-channel stages, ResNet-50 bottlenecks) need more than the default,
# so calls whose plan exceeds 16 MiB raise the limit via compiler_params.
_CONV_VMEM_BUDGET = 40 << 20
_SCOPED_VMEM_DEFAULT = 16 << 20


def _conv_norm_kernel(kh, kw, x_ref, g_ref, out_ref):
    """Unit-stride offsets: out[b] = Σ_{o<kh×kw} ‖Σ_s x[b, s+o] g[b, s]‖²_F."""
    xb = x_ref[...]                       # [TB, Hp, Wp, C]
    gb = g_ref[...]                       # [TB, Ho, Wo, K]
    tb, ho, wo, k = gb.shape
    g2 = gb.reshape(tb, ho * wo, k)
    total = jnp.zeros((tb, 1), jnp.float32)
    for oy in range(kh):
        for ox in range(kw):
            xs = xb[:, oy:oy + ho, ox:ox + wo, :]
            m = jax.lax.dot_general(       # [TB, C, K]: contraction over S
                xs.reshape(tb, ho * wo, xs.shape[-1]), g2,
                dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            msq = jnp.sum(m * m, axis=2)   # keep ranks >= 2 for Mosaic layouts
            total = total + jnp.sum(msq, axis=1, keepdims=True)
    out_ref[...] = total


def _conv_norm_catdot_kernel(kh, kw, x_ref, g_ref, out_ref):
    """Cross-product "cat-dot" form: ONE dot computes ALL kh·kw offset blocks.

    Key identity: lane-concatenate the ``kh`` ROW-shifted views of padded x
    (row slices — the H dim is untiled, so these are free offsets) into
    ``A [S', kh·C]`` with ``S' = Ho·Wp``, and the ``kw`` COLUMN-shifted
    zero-embedded copies of g into ``G [S', kw·K]``. Then
    ``(AᵀG)[(oy,c'),(ox,k')] = Σ_{r,w} x[r+oy, w+ox, c'] · g[r, w, k']
    = M_{(oy,ox)}[c',k']`` — every [C, K] block of the single ``[kh·C, kw·K]``
    product is exactly one kernel-offset's per-example weight-grad matrix, so
    ``‖∂W‖² = Σ (AᵀG)²`` with NO wasted cross terms. Versus the per-offset
    kernel this replaces kh·kw quarter-filled [C, K] dots (25% MXU fill at
    C = K = 64) with one [kh·C, kw·K] dot (56% fill at stage-1 geometry,
    100% at C = K = 128) and materializes 2 concatenated operands instead of
    kh·kw shifted windows. Cost over the direct form: only the Wp/Wo
    contraction-padding ratio (≈ 6%). Needs a raised scoped-VMEM limit for
    the wide operands — set via compiler_params at the call site."""
    xb = x_ref[...]                       # [TB, Hp, Wp, C]
    gb = g_ref[...]                       # [TB, Ho, Wo, K]
    tb, ho, wo, k = gb.shape
    wp = xb.shape[2]
    a = jnp.concatenate([xb[:, oy:oy + ho] for oy in range(kh)], axis=-1) \
        if kh > 1 else xb[:, :ho]
    gcols = []
    for ox in range(kw):
        parts = []
        if ox:
            parts.append(jnp.zeros((tb, ho, ox, k), gb.dtype))
        parts.append(gb)
        if wp - wo - ox:
            parts.append(jnp.zeros((tb, ho, wp - wo - ox, k), gb.dtype))
        gcols.append(jnp.concatenate(parts, axis=2) if len(parts) > 1 else gb)
    g_cat = jnp.concatenate(gcols, axis=-1) if kw > 1 else gcols[0]
    m = jax.lax.dot_general(              # [TB, kh·C, kw·K]
        a.reshape(tb, ho * wp, -1), g_cat.reshape(tb, ho * wp, -1),
        dimension_numbers=(((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    out_ref[...] = jnp.sum(jnp.sum(m * m, axis=2), axis=1, keepdims=True)


def _conv_need_bytes(hp, wp, c, ho, wo, k, itemsize, tile: int = 8) -> int:
    """Estimated per-grid-step VMEM bytes for the BlockSpec conv kernels."""
    lane = 128
    cpad, kpad = -(-c // lane) * lane, -(-k // lane) * lane
    per_ex = (hp * wp * cpad + ho * wo * kpad) * itemsize + cpad * kpad * 4
    return 2 * tile * per_ex                         # ×2: double-buffer margin


def _conv_tile_b(hp, wp, c, ho, wo, k, itemsize) -> int:
    """Largest batch tile whose working set fits the VMEM budget (0 = none).

    Tiles below 8 are NOT offered: the output block is ``(tile, 1)`` and
    Mosaic requires its sublane dim divisible by 8 — a tile of 4 compiles in
    interpret mode but crashes the hardware lowering."""
    for tile in (8,):
        if _conv_need_bytes(hp, wp, c, ho, wo, k, itemsize,
                            tile) <= _CONV_VMEM_BUDGET:
            return tile
    return 0


# The 16 MiB scoped-VMEM default is a compiler knob, not the hardware size —
# v5e compiles and runs these kernels with a raised limit. The cat-dot kernel
# trades VMEM (wide concatenated operands) for MXU fill, so it asks for more.
_CATDOT_VMEM_CAP = 96 << 20


def _catdot_vmem(hp, wp, c, ho, wo, k, kh, kw, itemsize) -> int:
    """Estimated scoped-VMEM bytes for the cat-dot kernel at batch tile 8."""
    lane, tile = 128, 8

    def pad8(v):
        return -(-v // 8) * 8

    def padl(v):
        return -(-v // lane) * lane

    cpad, kpad = padl(c), padl(k)
    blocks = 2 * tile * (hp * pad8(wp) * cpad
                         + ho * pad8(wo) * kpad) * itemsize    # double-buffered
    acat = tile * ho * pad8(wp) * padl(kh * c) * itemsize
    gcat = tile * ho * pad8(wp) * padl(kw * k) * itemsize
    m = tile * pad8(kh * c) * padl(kw * k) * 4
    # Build temporaries roughly double BOTH concatenated operands: the kh
    # row-shifted a slices and the kw zero-embedded g columns are each
    # materialized before their jnp.concatenate.
    return blocks + 2 * acat + 2 * gcat + m


def _catdot_ok(hp, wp, c, ho, wo, k, kh, kw, itemsize) -> bool:
    """Whether the cat-dot kernel applies: multi-offset conv with 128-aligned
    channels (the lane concatenations are then tile-appends; measured on-chip,
    64-channel operands relayout so heavily the per-offset kernel wins) and
    enough contraction depth to keep the MXU pipeline fed (short-S layers are
    latency-bound and belong to the v2/Gram kernels), fitting the raised
    VMEM cap."""
    if kh * kw < 2 or ho * wp < 128 or c % 128 or k % 128:
        return False
    return _catdot_vmem(hp, wp, c, ho, wo, k, kh, kw, itemsize) <= _CATDOT_VMEM_CAP


def _unit_stride_norm_sq(x_pad, g, kh, kw, interpret, catdot=False):
    """One pallas_call: all (kh, kw) offsets at unit stride. x_pad [B,Hp,Wp,C]
    must satisfy Hp >= kh-1+Ho, Wp >= kw-1+Wo. ``catdot`` selects the
    cross-product cat-dot kernel — the CALLER decides (and must have checked
    ``_catdot_ok``); the default is the per-offset kernel."""
    b, hp, wp, c = x_pad.shape
    ho, wo, k = g.shape[1:]
    tile = _conv_tile_b(hp, wp, c, ho, wo, k, x_pad.dtype.itemsize)
    assert tile > 0, "caller must check conv_grad_norm_pallas_fits first"
    if catdot:
        assert _catdot_ok(hp, wp, c, ho, wo, k, kh, kw, x_pad.dtype.itemsize)
    (x_pad, g), b_pad = _pad_batch([x_pad, g], b, tile)
    if catdot:
        kernel = functools.partial(_conv_norm_catdot_kernel, kh, kw)
        params = pltpu.CompilerParams(vmem_limit_bytes=_CATDOT_VMEM_CAP)
    else:
        kernel = functools.partial(_conv_norm_kernel, kh, kw)
        # Wide-channel layers (WRN 160/320, R50 bottlenecks) exceed the
        # 16 MiB scoped-VMEM default — raise the compiler limit for them.
        # Margin is 2.5× the block-level estimate: Mosaic's stack allocator
        # also holds the per-offset reshape copies, and 2× measured 4 % short
        # at WRN's 32²×160 geometry (43.84 MiB actual vs 42.06 MiB limit —
        # the round-5 remote-compile failure, tools/probe_wrn_compile.py).
        need = _conv_need_bytes(hp, wp, c, ho, wo, k, x_pad.dtype.itemsize,
                                tile)
        params = (pltpu.CompilerParams(
                      vmem_limit_bytes=min(5 * need // 2, _CATDOT_VMEM_CAP))
                  if 5 * need // 2 > _SCOPED_VMEM_DEFAULT else None)
    out = pl.pallas_call(
        kernel,
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, hp, wp, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, ho, wo, k), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        compiler_params=params,
        interpret=_auto_interpret(interpret),
    )(x_pad, g)
    return out[:b, 0]


def _grow(x_pad, min_h, min_w):
    """Zero-pad the spatial dims up to (min_h, min_w); extra rows are never read
    at offsets that matter, they only make contiguous slices well-formed."""
    ph = max(0, min_h - x_pad.shape[1])
    pw = max(0, min_w - x_pad.shape[2])
    if ph or pw:
        x_pad = jnp.pad(x_pad, ((0, 0), (0, ph), (0, pw), (0, 0)))
    return x_pad


def conv_grad_norm_pallas_fits(x_shape, g_shape, kernel_size, strides,
                               itemsize: int = 2) -> bool:
    """Whether the fused kernel's working set fits VMEM for this layer."""
    kh, kw = kernel_size
    sy, sx = strides
    ho, wo, k = g_shape[1:]
    hp = (kh - 1) // sy + ho + 1
    wp = (kw - 1) // sx + wo + 1
    c = x_shape[-1]
    return _conv_tile_b(hp, wp, c, ho, wo, k, itemsize) > 0


@functools.partial(jax.jit, static_argnames=("kernel_size", "strides", "padding",
                                             "interpret", "catdot"))
def conv_grad_norm_sq_pallas(x: jax.Array, g: jax.Array, kernel_size, strides,
                             padding, interpret: bool | None = None,
                             catdot: bool = False) -> jax.Array:
    """[B] ⟵ ‖per-example conv weight gradient‖²_F, fully fused in VMEM.

    ``x`` [B, H, W, C] is the conv input, ``g`` [B, Ho, Wo, K] the per-example
    cotangent at the conv output; ``padding`` is explicit ((lo,hi),(lo,hi)).
    Strided convs run as ``sy*sx`` unit-stride phase calls: offset (oy, ox)
    belongs to phase (oy % sy, ox % sx) and becomes offset (oy//sy, ox//sx) on
    the phase-strided input — the offsets of one phase are contiguous, so each
    phase is a smaller unit-stride kernel. ``catdot`` (unit-stride only,
    caller must have checked ``_catdot_ok``) selects the cross-product kernel.
    """
    kh, kw = kernel_size
    sy, sx = strides
    ho, wo, _ = g.shape[1:]
    x_pad = jnp.pad(x, ((0, 0), padding[0], padding[1], (0, 0)))
    if sy == 1 and sx == 1:
        return _unit_stride_norm_sq(_grow(x_pad, kh - 1 + ho, kw - 1 + wo),
                                    g, kh, kw, interpret, catdot=catdot)
    total = jnp.zeros(x.shape[0], jnp.float32)
    for py in range(sy):
        for px in range(sx):
            khp = len(range(py, kh, sy))
            kwp = len(range(px, kw, sx))
            if khp == 0 or kwp == 0:
                continue
            x_phase = x_pad[:, py::sy, px::sx, :]      # phase view (XLA slice)
            x_phase = _grow(x_phase, khp - 1 + ho, kwp - 1 + wo)
            total = total + _unit_stride_norm_sq(x_phase, g, khp, kwp, interpret)
    return total


# --------------------------------------------------------------------------
# Layout-persistent megakernel: conv backward + weight-grad-norm, ONE launch.
# --------------------------------------------------------------------------
#
# The round-5 ceiling analysis (PERFORMANCE.md) pinned ~26 ms of the 74.9 ms
# batch-1024 pass on kernel-boundary composition: the cotangent g of every
# conv layer is materialized by XLA's conv backward, leaves VMEM, and is
# re-staged (with a layout transition) into the per-layer contraction kernel.
# The fused-custom_vjp experiment proved the cost is NOT graph structure —
# moving the contraction next to the backward op changed nothing — so the
# remaining attack is to make the backward and the contraction the SAME
# kernel: this megakernel computes, per layer, BOTH
#
#   dx[b] = conv_transpose(g[b], W)         (the layer's input cotangent)
#   ‖∂W_b‖² = Σ_o ‖Σ_s x[b, s+o] g[b, s]‖²  (the per-example weight-grad norm)
#
# from one VMEM residency of the g tile. It is wired in through a custom_vjp
# tap (grand_batched._make_mega_tap) that supplies dx as the conv INPUT's
# cotangent and zeros the conv's own backward out of the graph.
#
# Stage-1 example packing, revisited: at C = K = 64 each per-offset [C, K]
# dot fills 25 % of the 128×128 MXU — 43 % of contraction time ran at
# 21.6 TF/s because of it. Lane-concatenating TWO examples' x and g tiles
# ([S, 2C] × [S, 2K] → [2C, 2K]) computes both examples' M blocks on the
# diagonal at 100 % lane fill; the off-diagonal cross-example blocks are
# wasted FLOPs (2× work, 4× fill → net 2× ceiling). Round 3 built this as a
# standalone kernel and measured it SLOWER — the pack/unpack copies were new
# kernel boundaries; here the operands are already VMEM-resident, so the
# pack is a register shuffle and the trade is re-measured, not assumed
# (tools/bisect_grand.py `megakernel` combos).

_MEGA_VMEM_CAP = 96 << 20


def _mega_need_bytes(hp, wp, c, ho, wo, k, kh, kw, itemsize,
                     tile: int = 8) -> int:
    """Estimated per-grid-step VMEM bytes for the megakernel."""
    lane = 128
    cpad, kpad = -(-c // lane) * lane, -(-k // lane) * lane
    blocks = 2 * tile * (hp * wp * cpad + ho * wo * kpad) * itemsize
    gbig = tile * (hp + kh - 1) * (wp + kw - 1) * kpad * itemsize
    dx_out = tile * hp * wp * cpad * 4
    dx_acc = tile * hp * wp * cpad * 4
    m = tile * cpad * kpad * 4
    wgt = kh * kw * cpad * kpad * 4
    return blocks + gbig + 2 * dx_out + dx_acc + m + wgt


def conv_bwd_norm_eligible(x_shape, g_shape, kernel_size, strides,
                           itemsize: int = 2) -> bool:
    """Whether the megakernel can run this layer: unit stride (the strided
    entry/projection layers are small and stay on the two-phase path) and a
    working set inside the raised scoped-VMEM cap."""
    if tuple(strides) != (1, 1):
        return False
    kh, kw = kernel_size
    c = x_shape[-1]
    ho, wo, k = g_shape[1:]
    hp, wp = kh - 1 + ho, kw - 1 + wo
    return _mega_need_bytes(hp, wp, c, ho, wo, k, kh, kw,
                            itemsize) <= _MEGA_VMEM_CAP


def _conv_bwd_norm_kernel(kh, kw, pack, use_bias,
                          x_ref, g_ref, w_ref, dx_ref, out_ref, gbig):
    """dx_pad AND ‖∂W‖² from one residency of the g tile.

    ``gbig`` is g zero-embedded at spatial offset (kh-1, kw-1) so every
    shifted window the transposed conv needs is a contiguous slice."""
    xb = x_ref[...]                       # [TB, Hp, Wp, C]
    gb = g_ref[...]                       # [TB, Ho, Wo, K]
    wgt = w_ref[...]                      # [kh, kw, C, K]
    tb, ho, wo, k = gb.shape
    hp, wp, c = xb.shape[1], xb.shape[2], xb.shape[3]
    s = ho * wo
    g2 = gb.reshape(tb, s, k)

    # ---- weight-grad-norm contraction (per offset, g tile shared) ----
    if pack:
        # C = K = 64: two examples per dot, diagonal blocks are the two Ms.
        ge = jnp.concatenate([g2[0::2], g2[1::2]], axis=-1)   # [TB/2, S, 2K]
        te = jnp.zeros((tb // 2, 1), jnp.float32)
        to = jnp.zeros((tb // 2, 1), jnp.float32)
        for oy in range(kh):
            for ox in range(kw):
                xs = xb[:, oy:oy + ho, ox:ox + wo, :].reshape(tb, s, c)
                xe = jnp.concatenate([xs[0::2], xs[1::2]], axis=-1)
                m = jax.lax.dot_general(   # [TB/2, 2C, 2K]
                    xe, ge, dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                msq = m * m
                te = te + jnp.sum(jnp.sum(msq[:, :c, :k], axis=2), axis=1,
                                  keepdims=True)
                to = to + jnp.sum(jnp.sum(msq[:, c:, k:], axis=2), axis=1,
                                  keepdims=True)
        total = jnp.concatenate([te, to], axis=1).reshape(tb, 1)
    else:
        total = jnp.zeros((tb, 1), jnp.float32)
        for oy in range(kh):
            for ox in range(kw):
                xs = xb[:, oy:oy + ho, ox:ox + wo, :]
                m = jax.lax.dot_general(   # [TB, C, K]
                    xs.reshape(tb, s, c), g2,
                    dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                total = total + jnp.sum(jnp.sum(m * m, axis=2), axis=1,
                                        keepdims=True)
    if use_bias:
        gsum = jnp.sum(g2.astype(jnp.float32), axis=1)
        total = total + jnp.sum(gsum * gsum, axis=1, keepdims=True)
    out_ref[...] = total

    # ---- input cotangent: dx_pad[y, x] = Σ_o g[y-oy, x-ox] · W[oy, ox]ᵀ ----
    gbig[...] = jnp.zeros_like(gbig)
    gbig[:, kh - 1:kh - 1 + ho, kw - 1:kw - 1 + wo, :] = gb
    acc = jnp.zeros((tb, hp * wp, c), jnp.float32)
    for oy2 in range(kh):
        for ox2 in range(kw):
            gs = gbig[:, oy2:oy2 + hp, ox2:ox2 + wp, :]
            acc = acc + jax.lax.dot_general(   # contract K: [TB, Hp·Wp, C]
                gs.reshape(tb, hp * wp, k), wgt[kh - 1 - oy2, kw - 1 - ox2],
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
    dx_ref[...] = acc.reshape(tb, hp, wp, c)


@functools.partial(jax.jit, static_argnames=("kernel_size", "padding",
                                             "use_bias", "interpret"))
def conv_bwd_grad_norm_sq_pallas(x: jax.Array, g: jax.Array, wgt: jax.Array,
                                 kernel_size, padding, use_bias: bool = False,
                                 interpret: bool | None = None):
    """(dx [B, H, W, C], norm_sq [B]) ⟵ the conv input cotangent and the
    per-example weight-grad norm² (+ bias-grad² when ``use_bias``) in ONE
    kernel launch — unit-stride convs, explicit ``padding`` pairs.

    ``wgt`` is the conv kernel [kh, kw, C, K]; ``dx`` is returned in
    ``x.dtype`` (f32-accumulated). The caller decides example packing is
    never exposed: C = K = 64 layers pack automatically."""
    kh, kw = kernel_size
    b, h, w_in, c = x.shape
    ho, wo, k = g.shape[1:]
    x_pad = jnp.pad(x, ((0, 0), padding[0], padding[1], (0, 0)))
    x_pad = _grow(x_pad, kh - 1 + ho, kw - 1 + wo)
    hp, wp = x_pad.shape[1:3]
    tile = 8
    (x_pad, g), b_pad = _pad_batch([x_pad, g], b, tile)
    pack = c == 64 and k == 64
    need = _mega_need_bytes(hp, wp, c, ho, wo, k, kh, kw,
                            x_pad.dtype.itemsize, tile)
    params = (pltpu.CompilerParams(
                  vmem_limit_bytes=min(5 * need // 2, _MEGA_VMEM_CAP))
              if 5 * need // 2 > _SCOPED_VMEM_DEFAULT else None)
    dx_pad, out = pl.pallas_call(
        functools.partial(_conv_bwd_norm_kernel, kh, kw, pack, use_bias),
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, hp, wp, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, ho, wo, k), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, c, k), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile, hp, wp, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b_pad, hp, wp, c), jnp.float32),
            jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hp + kh - 1, wp + kw - 1, k), g.dtype),
        ],
        compiler_params=params,
        interpret=_auto_interpret(interpret),
    )(x_pad, g, wgt)
    pt, plft = padding[0][0], padding[1][0]
    dx = dx_pad[:b, pt:pt + h, plft:plft + w_in, :].astype(x.dtype)
    return dx, out[:b, 0]


# --------------------------------------------------------------------------
# v2 conv weight-grad-norm kernel: raw (unpadded) x staged by manual DMA.
# --------------------------------------------------------------------------
#
# The v1 kernel takes pre-padded x, which costs one XLA `pad` (HBM write+read
# of the whole activation) plus a layout copy per layer — profiled at ~1/3 of
# the whole scoring pass across 13 conv layers. v2 takes RAW x and g in ANY
# (HBM) memory space and stages them itself: x rows are DMA'd into a
# zero-bordered VMEM buffer whose interior sits at an 8-aligned column offset
# (DMA stores must be sublane-aligned; reads of the shifted offset windows may
# be unaligned). SAME/explicit padding then costs nothing — the border zeros
# live only in VMEM, once.
#
# Eligibility: unit stride, and channel count a multiple of 128 (slicing a
# lane-padded HBM memref for the DMA is unsupported by Mosaic) — i.e. the
# C>=128 stages of the zoo, which are exactly the layers where the per-offset
# [C, K] contraction fills full MXU tiles. 64-channel and strided layers stay
# on v1; tiny-F layers (stem) on XLA.

_V2_COL0 = 8           # interior column offset (8-aligned DMA store)
_V2_VMEM_BUDGET = 12 << 20
_V2_ROW_TARGET = 256   # output rows per dot chunk ~ contraction depth


def _stage_geometry(x_shape, g_shape, kernel_size, strides, padding):
    """Shared staging geometry for the raw-x DMA kernels (v2 direct + Gram).

    Returns ``(rows, cols, w8, wo8)`` or None. Gates common to both kernels:
    unit stride; channels multiples of 128 (slicing a lane-padded HBM memref
    for DMA is unsupported); left padding ≤ the interior column offset. Widths
    are normalized to the 8-sublane DMA granule — the wrappers zero-pad narrow
    maps (extra g columns contribute nothing; extra x columns sit exactly where
    the virtual SAME padding is zero)."""
    kh, kw = kernel_size
    if tuple(strides) != (1, 1):
        return None
    _b, _h, w, c = x_shape
    ho, wo, k = g_shape[1:]
    if c % 128 != 0 or k % 128 != 0:
        return None
    if padding[1][0] > _V2_COL0:
        return None
    w8 = w + (-w) % 8
    wo8 = wo + (-wo) % 8
    rows = kh - 1 + ho
    need = _V2_COL0 + max(w8, wo8 + kw - 1)
    cols = need + (-need) % 8
    return rows, cols, w8, wo8


def _normalize_widths(x, g, w8, wo8):
    """Zero-pad the W dims up to the planned 8-aligned widths (see above)."""
    if g.shape[2] != wo8:
        g = jnp.pad(g, ((0, 0), (0, 0), (0, wo8 - g.shape[2]), (0, 0)))
    if x.shape[2] != w8:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, w8 - x.shape[2]), (0, 0)))
    return x, g


def _stage_dma(x_hbm, g_hbm, xbuf, gbuf, sem, i, tile, pt, h, w):
    """Kernel-side preamble shared by the DMA kernels: zero the bordered x
    buffer (virtual padding; zeroed every step — interpret mode does not
    guarantee scratch persistence, and on TPU this memset is ~µs against
    ~100µs of matmuls) and stage x rows + g."""
    xbuf[...] = jnp.zeros_like(xbuf)
    dx = pltpu.make_async_copy(
        x_hbm.at[pl.ds(i * tile, tile)],
        xbuf.at[:, pl.ds(pt, h), pl.ds(_V2_COL0, w), :], sem.at[0])
    dg = pltpu.make_async_copy(g_hbm.at[pl.ds(i * tile, tile)], gbuf, sem.at[1])
    dx.start()
    dg.start()
    dx.wait()
    dg.wait()


def _conv_v2_plan(x_shape, g_shape, kernel_size, strides, padding,
                  itemsize: int = 2):
    """(rows, cols, rc, w8, wo8) if v2 can run this layer, else None."""
    geo = _stage_geometry(x_shape, g_shape, kernel_size, strides, padding)
    if geo is None:
        return None
    rows, cols, w8, wo8 = geo
    c = x_shape[-1]
    ho, k = g_shape[1], g_shape[3]
    if c > 512 or k > 512:
        return None
    rc = max(1, min(ho, _V2_ROW_TARGET // wo8))
    tile = 8
    xbuf = rows * cols * c * itemsize
    gbuf = ho * wo8 * (-(-k // 128) * 128) * itemsize
    macc = c * (-(-k // 128) * 128) * 4
    temps = 2 * rc * wo8 * (c + (-(-k // 128) * 128)) * itemsize  # reshapes
    if tile * (xbuf + gbuf + macc + temps) > _V2_VMEM_BUDGET:
        return None
    return rows, cols, rc, w8, wo8


def conv_grad_norm_v2_eligible(x_shape, g_shape, kernel_size, strides,
                               padding, itemsize: int = 2) -> bool:
    """``padding`` is the explicit ((top, bottom), (left, right)) pairs — it
    participates in eligibility (left pad must fit before the interior
    column), so it is required, not defaulted."""
    return _conv_v2_plan(x_shape, g_shape, kernel_size, strides, padding,
                         itemsize) is not None


def _conv_v2_kernel(kh, kw, pt, plft, h, w, rc, use_bias,
                    x_hbm, g_hbm, out_ref, xbuf, gbuf, macc, sem):
    i = pl.program_id(0)
    tile = gbuf.shape[0]
    ho, wo, k = gbuf.shape[1:]
    c = xbuf.shape[-1]
    _stage_dma(x_hbm, g_hbm, xbuf, gbuf, sem, i, tile, pt, h, w)

    first = True
    for oy in range(kh):
        for ox in range(kw):
            macc[...] = jnp.zeros_like(macc)
            for r0 in range(0, ho, rc):
                nr = min(rc, ho - r0)
                xs = xbuf[:, oy + r0:oy + r0 + nr,
                          _V2_COL0 - plft + ox:_V2_COL0 - plft + ox + wo, :]
                gs = gbuf[:, r0:r0 + nr]
                macc[...] += jax.lax.dot_general(
                    xs.reshape(tile, nr * wo, c), gs.reshape(tile, nr * wo, k),
                    (((1,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
            m = macc[...]
            part = jnp.sum(jnp.sum(m * m, axis=2), axis=1, keepdims=True)
            out_ref[...] = part if first else out_ref[...] + part
            first = False
    if use_bias:
        gsum = jnp.sum(gbuf[...].astype(jnp.float32).reshape(tile, ho * wo, k),
                       axis=1)
        out_ref[...] += jnp.sum(gsum * gsum, axis=1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("kernel_size", "padding",
                                             "use_bias", "interpret"))
def conv_grad_norm_sq_v2(x: jax.Array, g: jax.Array, kernel_size, padding,
                         use_bias: bool = False,
                         interpret: bool | None = None) -> jax.Array:
    """[B] ⟵ ‖per-example conv weight gradient‖²_F (+ bias-grad² when
    ``use_bias``), unit-stride conv, raw unpadded ``x`` — padding is virtual
    (zero borders staged in VMEM). See the v2 design note above."""
    kh, kw = kernel_size
    (pt, _pb), (plft, _pr) = padding
    b, h, w, c = x.shape
    ho, wo, k = g.shape[1:]
    plan = _conv_v2_plan(x.shape, g.shape, kernel_size, (1, 1), padding,
                         x.dtype.itemsize)
    assert plan is not None, "caller must check conv_grad_norm_v2_eligible"
    rows, cols, rc, w8, wo8 = plan
    x, g = _normalize_widths(x, g, w8, wo8)
    w, wo = w8, wo8
    tile = 8
    (x, g), b_pad = _pad_batch([x, g], b, tile)
    out = pl.pallas_call(
        functools.partial(_conv_v2_kernel, kh, kw, pt, plft, h, w, rc, use_bias),
        grid=(b_pad // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((tile, rows, cols, c), x.dtype),
            pltpu.VMEM((tile, ho, wo, k), g.dtype),
            pltpu.VMEM((tile, c, k), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=_auto_interpret(interpret),
    )(x, g)
    return out[:b, 0]


# --------------------------------------------------------------------------
# Fused Gram-form conv weight-grad-norm kernel (small-S, wide-channel layers).
# --------------------------------------------------------------------------
#
# For late layers (stage 4: S = 16 output positions, F·K ≈ 2.4M) the Gram form
# ``‖PᵀG‖² = Σ_{ss'} (PPᵀ)_{ss'}(GGᵀ)_{ss'}`` costs ~15× fewer FLOPs than the
# direct contraction, but XLA's version materializes the [B, S, F] patch tensor
# and the [B, S, S] grams in HBM and was profiled at ~7 TF/s-equivalent. Here
# the im2col patches are BUILT IN VMEM (scratch stores at o·C lane offsets —
# aligned because every eligible layer has C a multiple of 128), the two tiny
# grams and their dot stay in registers, and x/g are staged raw by the same
# virtual-padding DMA as the v2 direct kernel.

_GRAM_MAX_S = 64


def _conv_gram_plan(x_shape, g_shape, kernel_size, strides, padding,
                    itemsize: int = 2):
    kh, kw = kernel_size
    geo = _stage_geometry(x_shape, g_shape, kernel_size, strides, padding)
    if geo is None:
        return None
    rows, cols, w8, wo8 = geo
    c = x_shape[-1]
    ho, k = g_shape[1], g_shape[3]
    s = ho * wo8
    if s > _GRAM_MAX_S:
        return None
    tile = 8
    spad = -(-s // 8) * 8
    vmem = tile * (rows * cols * c * itemsize          # xbuf
                   + ho * wo8 * k * itemsize           # gbuf
                   + spad * kh * kw * c * itemsize     # patches scratch
                   + 3 * spad * max(spad, 128) * 4)    # pp, gg, product
    if vmem > _V2_VMEM_BUDGET:
        return None
    return rows, cols, w8, wo8


def conv_grad_norm_gram_eligible(x_shape, g_shape, kernel_size, strides,
                                 padding, itemsize: int = 2) -> bool:
    return _conv_gram_plan(x_shape, g_shape, kernel_size, strides, padding,
                           itemsize) is not None


def _conv_gram_kernel(kh, kw, pt, plft, h, w, use_bias,
                      x_hbm, g_hbm, out_ref, xbuf, gbuf, pbuf, sem):
    i = pl.program_id(0)
    tile = gbuf.shape[0]
    ho, wo, k = gbuf.shape[1:]
    c = xbuf.shape[-1]
    s = ho * wo
    _stage_dma(x_hbm, g_hbm, xbuf, gbuf, sem, i, tile, pt, h, w)

    # Patches in VMEM: pbuf[:, s, o*C:(o+1)*C] = shifted x window (lane offset
    # o*C is 128-aligned for every eligible layer).
    for oi, (oy, ox) in enumerate((oy, ox) for oy in range(kh)
                                  for ox in range(kw)):
        win = xbuf[:, oy:oy + ho,
                   _V2_COL0 - plft + ox:_V2_COL0 - plft + ox + wo, :]
        pbuf[:, :, oi * c:(oi + 1) * c] = win.reshape(tile, s, c)

    p = pbuf[...]
    g2 = gbuf[...].reshape(tile, s, k)
    pp = jax.lax.dot_general(p, p, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    gg = jax.lax.dot_general(g2, g2, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    acc = jnp.sum(jnp.sum(pp * gg, axis=2), axis=1, keepdims=True)
    if use_bias:
        gsum = jnp.sum(g2.astype(jnp.float32), axis=1)
        acc = acc + jnp.sum(gsum * gsum, axis=1, keepdims=True)
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("kernel_size", "padding",
                                             "use_bias", "interpret"))
def conv_grad_norm_sq_gram(x: jax.Array, g: jax.Array, kernel_size, padding,
                           use_bias: bool = False,
                           interpret: bool | None = None) -> jax.Array:
    """[B] ⟵ Gram-form ‖per-example conv weight gradient‖²_F (+ bias-grad²),
    unit-stride conv, raw unpadded ``x``; see the design note above."""
    kh, kw = kernel_size
    (pt, _pb), (plft, _pr) = padding
    b, h, w, c = x.shape
    ho, wo, k = g.shape[1:]
    plan = _conv_gram_plan(x.shape, g.shape, kernel_size, (1, 1), padding,
                           x.dtype.itemsize)
    assert plan is not None, "caller must check conv_grad_norm_gram_eligible"
    rows, cols, w8, wo8 = plan
    x, g = _normalize_widths(x, g, w8, wo8)
    w, wo = w8, wo8
    tile = 8
    (x, g), b_pad = _pad_batch([x, g], b, tile)
    out = pl.pallas_call(
        functools.partial(_conv_gram_kernel, kh, kw, pt, plft, h, w, use_bias),
        grid=(b_pad // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((tile, rows, cols, c), x.dtype),
            pltpu.VMEM((tile, ho, wo, k), g.dtype),
            pltpu.VMEM((tile, ho * wo, kh * kw * c), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=_auto_interpret(interpret),
    )(x, g)
    return out[:b, 0]


# --------------------------------------------------------------------------
# Fused eval-mode BatchNorm grad-norm² kernel (stackable across layers).
# --------------------------------------------------------------------------
#
# The XLA form of the BN contribution (`grand_batched._bn_contrib`) is two
# f32 multiply+reduce passes per layer; profiled on-chip they run far below
# bandwidth (layout-hostile reductions) and each BN layer is its own fusion.
# This kernel computes ``Σ_c ((Σ_s g·x − μ·Σ_s g)·rstd)² [+ Σ_c (Σ_s g)²]``
# in ONE VMEM pass, and several same-shape layers can be STACKED along the
# leading axis (their per-layer (μ, rstd) rows are indexed by segment) — one
# kernel launch for e.g. all five [B, 8, 8, 256] BatchNorms of a ResNet-18.

_BN_VMEM_BUDGET = 10 << 20


def _bn_tile(h, w, c, itemsize) -> int:
    lane = 128
    cpad = -(-c // lane) * lane
    per_ex = 2 * h * w * cpad * itemsize          # x + g blocks
    for tile in (128, 64, 32, 16, 8):
        if 2 * tile * per_ex <= _BN_VMEM_BUDGET:  # ×2 double-buffer margin
            return tile
    return 0


def _bn_kernel(use_scale, use_bias, x_ref, g_ref, stats_ref, out_ref):
    x = x_ref[...]
    g = g_ref[...]
    tb, c = x.shape[0], x.shape[-1]
    xf = x.reshape(tb, -1, c).astype(jnp.float32)
    gf = g.reshape(tb, -1, c).astype(jnp.float32)
    gx = jnp.sum(gf * xf, axis=1)                 # [TB, C]
    gs = jnp.sum(gf, axis=1)
    mean = stats_ref[0, 0:1, :]
    rstd = stats_ref[0, 1:2, :]
    acc = jnp.zeros((tb, 1), jnp.float32)
    if use_scale:
        t = (gx - mean * gs) * rstd
        acc += jnp.sum(t * t, axis=1, keepdims=True)
    if use_bias:
        acc += jnp.sum(gs * gs, axis=1, keepdims=True)
    out_ref[...] = acc


def bn_grad_norm_fits(x_shape, itemsize: int = 2) -> bool:
    return _bn_tile(x_shape[1], x_shape[2], x_shape[3], itemsize) > 0


@functools.partial(jax.jit, static_argnames=("use_scale", "use_bias", "per_layer",
                                             "interpret"))
def bn_grad_norm_sq_pallas(x: jax.Array, g: jax.Array, stats: jax.Array,
                           per_layer: int, use_scale: bool = True,
                           use_bias: bool = True,
                           interpret: bool | None = None) -> jax.Array:
    """[N] ⟵ eval-mode BatchNorm per-example grad-norm², fused.

    ``x``/``g`` are [N, H, W, C] with ``N = n_layers · per_layer`` (same-shape
    layers stacked along the batch); ``stats`` is [n_layers, 8, C] — rows 0/1
    of each layer's slab hold (mean, rstd), rows 2-7 are sublane padding
    (Mosaic block shapes need 8-divisible second-minor dims). ``per_layer``
    must be a multiple of the batch tile so a grid step never straddles two
    layers' statistics.
    """
    n, h, w, c = x.shape
    tile = _bn_tile(h, w, c, x.dtype.itemsize)
    assert tile > 0, "caller must check bn_grad_norm_fits first"
    while per_layer % tile:
        tile //= 2
    assert tile >= 8 and n % tile == 0, (n, per_layer, tile)
    steps_per_layer = per_layer // tile
    out = pl.pallas_call(
        functools.partial(_bn_kernel, use_scale, use_bias),
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((tile, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, c), lambda i: (i // steps_per_layer, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(x, g, stats)
    return out[:, 0]


def _gll_kernel(feats_ref, w_ref, b_ref, labels_ref, mask_ref, out_ref):
    feats = feats_ref[:]
    logits = jnp.dot(feats, w_ref[:],
                     preferred_element_type=jnp.float32) + b_ref[:]
    err = _onehot_err(logits, labels_ref[:])
    err_sq = jnp.sum(err * err, axis=-1, keepdims=True)
    feat_sq = jnp.sum(feats * feats, axis=-1, keepdims=True)
    out_ref[:] = jnp.sqrt(err_sq * (feat_sq + 1.0)) * mask_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def grand_last_layer_pallas(features: jax.Array, kernel: jax.Array,
                            bias: jax.Array, labels: jax.Array, mask: jax.Array,
                            interpret: bool | None = None) -> jax.Array:
    """Last-layer GraNd [B] from features [B, F] and classifier (kernel [F, C],
    bias [C]); classifier matmul and score math fused in one kernel."""
    b, f = features.shape
    c = kernel.shape[1]
    tile = _tile_for(b)
    (feats, labels2, mask2), b_pad = _pad_batch(
        [features.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
         mask.astype(jnp.float32)[:, None]], b, tile)
    out = pl.pallas_call(
        _gll_kernel,
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((f, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(feats, kernel.astype(jnp.float32),
      bias.astype(jnp.float32)[None, :], labels2, mask2)
    return out[:b, 0]
