"""Pallas TPU kernels for the scoring hot path.

Two fused kernels (see /opt/skills/guides/pallas_guide.md for the API conventions):

* ``el2n_pallas`` — fused ``softmax -> subtract one-hot -> row L2 norm -> mask``
  over logits. One VMEM round-trip instead of four HBM-materialized intermediates.
* ``grand_last_layer_pallas`` — the closed-form last-layer GraNd
  (``‖p − y‖ · sqrt(‖h‖² + 1)``) fused WITH the classifier matmul: features hit the
  MXU against the classifier weights and the score math runs on the VPU before
  logits ever leave VMEM. The model's own Dense head output goes unused and is
  dead-code-eliminated under jit, so the classifier matmul happens exactly once.

Both kernels tile the batch dimension (``TILE_B`` rows per grid step, fp32-aligned)
and keep the class dimension whole (Mosaic pads the lane dimension internally).
Padded batch rows carry ``mask == 0`` and score 0. On non-TPU backends the kernels
run in interpreter mode, so every test exercises the same code path CI runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_B = 256  # batch rows per grid step; multiple of the fp32 sublane tile (8)


def _auto_interpret(interpret: bool | None) -> bool:
    return jax.default_backend() != "tpu" if interpret is None else interpret


def _tile_for(batch: int) -> int:
    """Largest fp32-sublane-aligned tile <= TILE_B covering the batch."""
    rounded = (batch + 7) // 8 * 8
    return min(TILE_B, rounded)


def _pad_batch(arrs, batch: int, tile: int):
    pad = (-batch) % tile
    if pad == 0:
        return arrs, batch + pad
    return [jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)) for a in arrs], batch + pad


def _onehot_err(logits, labels_col):
    """softmax(logits) − onehot(labels): the shared EL2N/GraNd error vector."""
    probs = jax.nn.softmax(logits, axis=-1)
    cols = jax.lax.broadcasted_iota(jnp.int32, probs.shape, 1)
    return probs - (cols == labels_col).astype(jnp.float32)


def _el2n_kernel(logits_ref, labels_ref, mask_ref, out_ref):
    err = _onehot_err(logits_ref[:], labels_ref[:])
    out_ref[:] = jnp.sqrt(jnp.sum(err * err, axis=-1, keepdims=True)) * mask_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def el2n_pallas(logits: jax.Array, labels: jax.Array, mask: jax.Array,
                interpret: bool | None = None) -> jax.Array:
    """EL2N scores [B] from logits [B, C]; fused single-pass kernel."""
    b, c = logits.shape
    tile = _tile_for(b)
    (logits, labels2, mask2), b_pad = _pad_batch(
        [logits.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
         mask.astype(jnp.float32)[:, None]], b, tile)
    out = pl.pallas_call(
        _el2n_kernel,
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, c), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(logits, labels2, mask2)
    return out[:b, 0]


def _gll_kernel(feats_ref, w_ref, b_ref, labels_ref, mask_ref, out_ref):
    feats = feats_ref[:]
    logits = jnp.dot(feats, w_ref[:],
                     preferred_element_type=jnp.float32) + b_ref[:]
    err = _onehot_err(logits, labels_ref[:])
    err_sq = jnp.sum(err * err, axis=-1, keepdims=True)
    feat_sq = jnp.sum(feats * feats, axis=-1, keepdims=True)
    out_ref[:] = jnp.sqrt(err_sq * (feat_sq + 1.0)) * mask_ref[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def grand_last_layer_pallas(features: jax.Array, kernel: jax.Array,
                            bias: jax.Array, labels: jax.Array, mask: jax.Array,
                            interpret: bool | None = None) -> jax.Array:
    """Last-layer GraNd [B] from features [B, F] and classifier (kernel [F, C],
    bias [C]); classifier matmul and score math fused in one kernel."""
    b, f = features.shape
    c = kernel.shape[1]
    tile = _tile_for(b)
    (feats, labels2, mask2), b_pad = _pad_batch(
        [features.astype(jnp.float32), labels.astype(jnp.int32)[:, None],
         mask.astype(jnp.float32)[:, None]], b, tile)
    out = pl.pallas_call(
        _gll_kernel,
        grid=(b_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((f, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, 1), jnp.float32),
        interpret=_auto_interpret(interpret),
    )(feats, kernel.astype(jnp.float32),
      bias.astype(jnp.float32)[None, :], labels2, mask2)
    return out[:b, 0]
