from .scores import (cross_entropy, el2n_from_logits, grand_last_layer_from_logits,
                     make_el2n_step, make_grand_last_layer_step, make_grand_step,
                     make_score_step)
from .scoring import score_dataset

__all__ = [
    "cross_entropy", "el2n_from_logits", "grand_last_layer_from_logits",
    "make_el2n_step", "make_grand_last_layer_step", "make_grand_step",
    "make_score_step", "score_dataset",
]
