"""Batched exact GraNd: per-example gradient norms without per-example backwards.

The naive full GraNd (``scores.make_grand_step``) is ``vmap(grad)`` over single
examples — each example's backward runs convolutions at batch size 1, which the
MXU cannot tile efficiently. This module computes the SAME quantity,
``‖∇_θ ℓ(f(x_i), y_i)‖₂`` over all parameters, from ONE batched forward and ONE
batched backward:

1. every ``Conv``/``Dense``/``BatchNorm`` output ``y`` gets a zero "perturbation"
   added (``flax`` interceptor — no model changes); the gradient of the summed
   per-example loss w.r.t. that zero is the **per-example cotangent** ``g_i`` at
   that layer output (activations are per-example, so unlike weight gradients
   these never sum over the batch);
2. each layer's per-example weight-gradient norm then has a closed form in terms
   of its input ``x_i`` (captured by the same interceptor) and ``g_i``:

   * Dense ``y = xW + b``:  ``∂ℓᵢ/∂W = xᵢ gᵢᵀ`` ⇒ ``‖∂W‖² = ‖xᵢ‖²·‖gᵢ‖²`` and
     ``‖∂b‖² = ‖gᵢ‖²`` (Goodfellow 2015's per-example-norm trick);
   * Conv: with ``P_i ∈ [S, F]`` the im2col patch matrix (``F = C·kh·kw``, ``S``
     output positions) and ``G_i ∈ [S, K]`` the cotangent, ``∂ℓᵢ/∂W = P_iᵀ G_i``
     ⇒ ``‖∂W‖²_F`` is either the direct contraction ``Σ_{fk}(P_iᵀG_i)²`` or the
     Gram form ``Σ_{ss'}(P_iP_iᵀ)_{ss'}(G_iG_iᵀ)_{ss'}`` — whichever is cheaper
     for the layer's geometry (direct for early layers where ``S`` is large,
     Gram for late layers where ``F·K`` dominates). Both are batched matmuls;
   * eval-mode BatchNorm ``y = γ·x̂ + β``: ``∂ℓᵢ/∂γ = Σ_s gᵢx̂ᵢ``,
     ``∂ℓᵢ/∂β = Σ_s gᵢ`` with ``x̂`` recomputed from the captured input and the
     (constant) running statistics.

Cost: one forward + one input-gradient backward + one MXU-friendly batched
contraction per parameterized layer — the same FLOPs as ``vmap(grad)`` but
executed as large matmuls instead of batch-1 convolutions.

Exactness requires eval-mode scoring (train-mode BatchNorm normalizes by batch
statistics, which couples examples; the ``vmap(grad)`` path normalizes each
example by itself there — neither is "the" per-example gradient, so the fast
path refuses and callers fall back to ``vmap(grad)``). Verified against
``vmap(grad)`` to float tolerance in ``tests/test_grand_batched.py``.

Reference context: the PyTorch reference has no GraNd at all (SURVEY §2.3 —
EL2N only, ``get_scores_and_prune.py:15-18``); full-parameter GraNd is the
BASELINE.json north-star capability, and this is its TPU-native fast path.
"""

from __future__ import annotations

from collections import Counter
from functools import reduce

import flax.linen as nn
import jax
import jax.numpy as jnp


_F32 = jnp.float32

# Composition toggles (module-level; DDT_GRAND_* env vars override so on-chip
# perf bisection can flip them per bench run without code edits).
# Conservative defaults: each True value must EARN its place by measured
# full-pass wins on v5e — individually-faster kernels have been observed to
# compose into a slower pass (layout/fusion interactions), so composition is
# bisected on hardware, not assumed.
import os as _os


_TOGGLE_TRUE = frozenset(("1", "true", "on", "yes"))
_TOGGLE_FALSE = frozenset(("0", "false", "off", "no", ""))


def _toggle(name: str, default: bool) -> bool:
    v = _os.environ.get(name)
    if v is None:
        return default
    s = v.strip().lower()
    if s in _TOGGLE_TRUE:
        return True
    if s in _TOGGLE_FALSE:
        return False
    # A typo in a bisection run must not silently enable an experimental
    # kernel path.
    raise ValueError(
        f"{name}={v!r}: expected one of "
        f"{sorted(_TOGGLE_TRUE | _TOGGLE_FALSE)}")


GROUP_CONV = _toggle("DDT_GRAND_GROUP_CONV", False)
GROUP_BN = _toggle("DDT_GRAND_GROUP_BN", False)
USE_BN_KERNEL = _toggle("DDT_GRAND_BN_KERNEL", False)
USE_CATDOT = _toggle("DDT_GRAND_CATDOT", False)
# Tiny-F convs (the 3-channel stem) via XLA's fused patch einsum instead of
# the Pallas path. Default ON: the round-5 on-chip bisection measured it the
# only winning toggle — 12,475-12,542 ex/s/chip vs 11,929-12,218 baseline
# (+4%, consistent across 3 runs; every other combo lost, bisect_results_r5*.json).
STEM_XLA = _toggle("DDT_GRAND_STEM_XLA", True)
# Contract each layer's cotangent INSIDE the backward pass (custom_vjp taps)
# instead of returning all cotangents from jax.grad and contracting afterwards
# (``batched_grand_scores_fused``). Attacks the ~26 ms/batch-1024 composition
# overhead the round-5 profile measured between the bwd and the contraction
# phase: cotangents are consumed where they are produced and never become
# grad *outputs*, so the all-layer cotangent pytree is no longer live at once.
FUSED_BWD = _toggle("DDT_GRAND_FUSED", False)
# Layout-persistent megakernel (``pallas_kernels.conv_bwd_grad_norm_sq_pallas``
# through the fused-tap machinery): for eligible unit-stride convs the layer
# BACKWARD and the weight-grad-norm contraction run in ONE Pallas launch,
# sharing the cotangent tile while it is VMEM-resident — the per-layer kernel
# boundary (layout transition out of the bwd custom call and back into the
# contraction call, the round-5-measured ~26 ms term the fused-custom_vjp
# parity result proved is NOT graph structure) disappears, and stage-1's
# 64-channel contractions are example-packed into full 128-lane tiles inside
# the same kernel. Default off: promotion is by on-chip bisection
# (tools/bisect_grand.py `megakernel` combos), never assumed.
MEGAKERNEL = _toggle("DDT_GRAND_MEGAKERNEL", False)


def _canon_tuple(v, n: int) -> tuple:
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _canon_padding(padding, n: int):
    if isinstance(padding, str):
        return padding
    if isinstance(padding, int):
        return ((padding, padding),) * n
    out = []
    for p in padding:
        out.append((p, p) if isinstance(p, int) else tuple(p))
    return tuple(out)


def _record_for(mod) -> dict:
    """Static per-layer metadata needed to rebuild the weight-grad norm later."""
    path = tuple(mod.path)
    if isinstance(mod, nn.Conv):
        n = len(mod.kernel_size)
        if n != 2:
            raise NotImplementedError(
                "batched GraNd supports 2-D convolutions only (module "
                f"{'/'.join(path)} has {n}-D kernel); use the grand_vmap "
                "score method")
        if mod.feature_group_count != 1:
            raise NotImplementedError(
                "batched GraNd supports feature_group_count=1 convolutions only "
                f"(module {'/'.join(path)}); use the grand_vmap score method")
        if _canon_tuple(mod.kernel_dilation, n) != (1,) * n or \
                _canon_tuple(mod.input_dilation, n) != (1,) * n:
            raise NotImplementedError(
                f"batched GraNd does not support dilated convolutions "
                f"(module {'/'.join(path)}); use the grand_vmap score method")
        padding = _canon_padding(mod.padding, n)
        if isinstance(padding, str) and padding not in ("SAME", "VALID"):
            # _explicit_padding implements XLA's SAME arithmetic only; any other
            # string (SAME_LOWER, CIRCULAR, ...) would silently compute wrong
            # norms — refuse loudly like the grouped/dilated-conv guards.
            raise NotImplementedError(
                f"batched GraNd supports SAME/VALID/explicit conv padding only "
                f"(module {'/'.join(path)} has {padding!r}); use the grand_vmap "
                "score method")
        return {"kind": "conv", "path": path,
                "kernel_size": tuple(mod.kernel_size),
                "strides": _canon_tuple(mod.strides, n),
                "padding": padding,
                "use_bias": mod.use_bias}
    if isinstance(mod, nn.Dense):
        return {"kind": "dense", "path": path, "use_bias": mod.use_bias}
    # BatchNorm. use_running_average may be resolved per-call; our zoo fixes it
    # at construction (models/resnet.py norm partial), so the attribute is truthy
    # in eval mode — the only mode this path accepts (module docstring).
    if mod.use_running_average is not True:
        raise ValueError(
            f"batched GraNd requires eval-mode BatchNorm (module {'/'.join(path)} "
            "has use_running_average != True); use the grand_vmap score method")
    return {"kind": "bn", "path": path, "epsilon": float(mod.epsilon),
            "use_scale": mod.use_scale, "use_bias": mod.use_bias}


def _make_interceptor(records: list | None):
    """Wrap every Conv/Dense/BatchNorm ``__call__``: capture the input into the
    ``ddt_in`` collection and add a zero perturbation (``ddt_pert``) to the
    output. ``records`` (when not None) collects the static layer metadata."""

    def interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if (context.method_name != "__call__"
                or not isinstance(mod, (nn.Conv, nn.Dense, nn.BatchNorm))
                or mod.scope is None):
            return next_fun(*args, **kwargs)
        if records is not None:
            records.append(_record_for(mod))
        mod.sow("ddt_in", "x", args[0], reduce_fn=lambda _, b: b, init_fn=lambda: 0)
        y = next_fun(*args, **kwargs)
        return mod.perturb("y", y, collection="ddt_pert")

    return interceptor


def _leaf(tree, path: tuple, name: str):
    return reduce(lambda d, k: d[k], path, tree)[name]


def _sq(x, axis):
    x = x.astype(_F32)
    return jnp.sum(x * x, axis=axis)


def _matrix_grad_norm_sq(p: jax.Array, go: jax.Array) -> jax.Array:
    """[B] ⟵ ``‖P_iᵀ G_i‖²_F`` for P [B, S, F], G [B, S, K] — the shared-weight
    per-example gradient norm (conv patches; Dense applied per position). Direct
    contraction or Gram form, whichever the layer geometry makes cheaper."""
    s, f, k = p.shape[1], p.shape[-1], go.shape[-1]
    if s * (f + k) < f * k:
        # Gram form: Σ_{ss'} (PPᵀ)(GGᵀ) — S² dominates F·K for late layers.
        pp = jnp.einsum("bsf,btf->bst", p, p, preferred_element_type=_F32)
        gg = jnp.einsum("bsk,btk->bst", go, go, preferred_element_type=_F32)
        return jnp.sum(pp * gg, axis=(1, 2))
    m = jnp.einsum("bsf,bsk->bfk", p, go, preferred_element_type=_F32)
    return jnp.sum(m * m, axis=(1, 2))


def _explicit_padding(padding, x: jax.Array, g: jax.Array, rec: dict):
    """Resolve string paddings to explicit pairs using XLA's SAME semantics."""
    if not isinstance(padding, str):
        return padding
    if padding == "VALID":
        return ((0, 0), (0, 0))
    out = []
    for d in (1, 2):
        total = max((g.shape[d] - 1) * rec["strides"][d - 1]
                    + rec["kernel_size"][d - 1] - x.shape[d], 0)
        out.append((total // 2, total - total // 2))
    return tuple(out)


# Prefer the direct-form Pallas kernels over the XLA Gram form as long as the
# direct FLOPs are within this factor of Gram's: measured on v5e, the fused
# kernels sustain ~4× the Gram einsum's throughput (no patch/M/pp/gg HBM
# materialization, full MXU tiles), so paying up to ~8× the FLOPs still wins
# or ties, and the stage-4 geometries (ratio ≥ 14) correctly stay on Gram.
_DIRECT_OVER_GRAM_MAX_RATIO = 8.0


def _conv_sfk(rec: dict, x_shape, g_shape) -> tuple[int, int, int]:
    """(S output positions, F patch width, K output channels) for a conv —
    the geometry every dispatch gate reasons in."""
    return (np_prod(g_shape[1:-1]),
            np_prod(rec["kernel_size"]) * x_shape[-1], g_shape[-1])


def _direct_form_ok(s: int, f: int, k: int) -> bool:
    """Direct-form kernels are eligible iff their FLOPs stay within the
    measured ratio of the Gram form's — THE predicate, shared by the
    two-phase dispatch (``_conv_contrib``) and the megakernel route
    (``_mega_conv_route``) so the two cannot drift."""
    return f * k <= _DIRECT_OVER_GRAM_MAX_RATIO * s * (f + k)


def _conv_bias_term(g: jax.Array, batch: int, s: int) -> jax.Array:
    """[B] squared norm of the per-example conv bias gradient ``Σ_s g``."""
    return _sq(jnp.sum(g.astype(_F32).reshape(batch, s, -1), axis=1), axis=-1)


def _conv_contrib(rec: dict, x: jax.Array, g: jax.Array,
                  use_pallas: bool = False) -> jax.Array:
    """[B] Frobenius-norm² of the per-example conv weight gradient ``P_iᵀ G_i``."""
    batch = x.shape[0]
    s, f, k = _conv_sfk(rec, x.shape, g.shape)
    gram = s * (f + k) < f * k
    # Kernel-eligible iff direct FLOPs are within the ratio of Gram's (the
    # not-gram case satisfies this by definition: f*k <= s*(f+k)).
    direct_ok = _direct_form_ok(s, f, k)
    if STEM_XLA and f < 32:
        # Tiny-F layers (the 3-channel stem) under-fill every MXU form; let
        # XLA's fused patch einsum take them (bisection toggle).
        use_pallas = False
    if use_pallas:
        from .pallas_kernels import (_catdot_ok, conv_grad_norm_gram_eligible,
                                     conv_grad_norm_pallas_fits,
                                     conv_grad_norm_sq_gram,
                                     conv_grad_norm_sq_pallas,
                                     conv_grad_norm_sq_v2,
                                     conv_grad_norm_v2_eligible)
        pad = _explicit_padding(rec["padding"], x, g, rec)
        ho, wo = g.shape[1:3]
        strides = tuple(rec["strides"])
        if (USE_CATDOT and direct_ok and strides == (1, 1) and s >= 256
                and _catdot_ok(x.shape[1] + pad[0][0] + pad[0][1],
                               x.shape[2] + pad[1][0] + pad[1][1],
                               x.shape[-1], ho, wo, k,
                               *rec["kernel_size"], x.dtype.itemsize)
                and conv_grad_norm_pallas_fits(
                    x.shape, g.shape, rec["kernel_size"], strides,
                    x.dtype.itemsize)):
            # Cat-dot beats the v2 direct kernel for deep-contraction
            # 128-aligned layers (stage-2 geometry: 53 vs 46 TF/s measured);
            # shallower layers stay on v2/Gram below.
            contrib = conv_grad_norm_sq_pallas(
                x, g, tuple(rec["kernel_size"]), strides, pad, catdot=True)
            if rec["use_bias"]:
                contrib = contrib + _conv_bias_term(g, batch, s)
            return contrib
        if direct_ok and conv_grad_norm_v2_eligible(
                x.shape, g.shape, rec["kernel_size"], rec["strides"], pad,
                x.dtype.itemsize):
            # Raw-x kernel: padding is virtual (VMEM zero borders), the bias
            # term is fused — no XLA pad, no second read of g.
            return conv_grad_norm_sq_v2(x, g, tuple(rec["kernel_size"]), pad,
                                        use_bias=rec["use_bias"])
        if gram and conv_grad_norm_gram_eligible(
                x.shape, g.shape, rec["kernel_size"], rec["strides"], pad,
                x.dtype.itemsize):
            # Fused Gram form: small-S wide-channel layers (stage 4), patches
            # built in VMEM, grams never touch HBM.
            return conv_grad_norm_sq_gram(x, g, tuple(rec["kernel_size"]), pad,
                                          use_bias=rec["use_bias"])
        if not gram and conv_grad_norm_pallas_fits(
                x.shape, g.shape, rec["kernel_size"], rec["strides"],
                x.dtype.itemsize):
            contrib = conv_grad_norm_sq_pallas(
                x, g, tuple(rec["kernel_size"]), tuple(rec["strides"]), pad)
            if rec["use_bias"]:
                contrib = contrib + _conv_bias_term(g, batch, s)
            return contrib
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=rec["kernel_size"], window_strides=rec["strides"],
        padding=rec["padding"],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    contrib = _matrix_grad_norm_sq(patches.reshape(batch, s, patches.shape[-1]),
                                   g.reshape(batch, s, g.shape[-1]))
    if rec["use_bias"]:
        contrib = contrib + _conv_bias_term(g, batch, s)
    return contrib


def np_prod(shape) -> int:
    out = 1
    for v in shape:
        out *= int(v)
    return out


def _dense_contrib(rec: dict, x: jax.Array, g: jax.Array) -> jax.Array:
    if x.ndim == 2:
        # Goodfellow's identity: ∂W = x gᵀ ⇒ ‖∂W‖² = ‖x‖²‖g‖².
        contrib = _sq(x, axis=1) * _sq(g, axis=1)
    else:
        # Dense applied per position ([B, ..., F]): the weight is SHARED across
        # positions, so ∂W = Σ_s x_s g_sᵀ — the factored identity does not hold;
        # use the same matrix contraction as conv patches.
        batch = x.shape[0]
        contrib = _matrix_grad_norm_sq(x.reshape(batch, -1, x.shape[-1]),
                                       g.reshape(batch, -1, g.shape[-1]))
    if rec["use_bias"]:
        gb = g.astype(_F32).reshape(g.shape[0], -1, g.shape[-1]).sum(axis=1)
        contrib = contrib + _sq(gb, axis=-1)
    return contrib


def _bn_stats(rec: dict, batch_stats) -> tuple[jax.Array, jax.Array]:
    scope = reduce(lambda d, k: d[k], rec["path"], batch_stats)
    return (scope["mean"].astype(_F32),
            jax.lax.rsqrt(scope["var"].astype(_F32) + rec["epsilon"]))


def _bn_group_contrib(items, batch_stats, use_pallas: bool) -> jax.Array:
    """Σ over same-shape BatchNorm layers of the per-example grad-norm².

    ``items`` is a list of ``(rec, x, g)`` with identical activation shapes;
    with Pallas available they are stacked along the batch and scored by ONE
    ``bn_grad_norm_sq_pallas`` launch (per-layer (μ, rstd) rows indexed by
    segment) instead of one XLA fusion per layer."""
    rec0, x0, _ = items[0]
    b = x0.shape[0]
    if use_pallas and USE_BN_KERNEL and x0.ndim == 4:
        from .pallas_kernels import bn_grad_norm_fits, bn_grad_norm_sq_pallas
        if bn_grad_norm_fits(x0.shape, x0.dtype.itemsize):
            b8 = -(-b // 8) * 8

            def padb(a):
                return jnp.pad(a, ((0, b8 - b),) + ((0, 0),) * (a.ndim - 1))

            xs = jnp.concatenate([padb(x) for _, x, _ in items], axis=0)
            gs = jnp.concatenate([padb(g) for _, _, g in items], axis=0)
            # [L, 8, C] stats slabs: rows 0/1 = (mean, rstd), rest sublane pad.
            stats = jnp.pad(
                jnp.stack([jnp.stack(_bn_stats(rec, batch_stats))
                           for rec, _, _ in items]),
                ((0, 0), (0, 6), (0, 0)))
            out = bn_grad_norm_sq_pallas(xs, gs, stats, b8,
                                         use_scale=rec0["use_scale"],
                                         use_bias=rec0["use_bias"])
            return jnp.sum(out.reshape(len(items), b8)[:, :b], axis=0)
    total = jnp.zeros(b, _F32)
    for rec, x, g in items:
        total = total + _bn_contrib(rec, x, g, batch_stats)
    return total


def _bn_contrib(rec: dict, x: jax.Array, g: jax.Array, batch_stats) -> jax.Array:
    """Eval-mode BatchNorm grad-norm² from two channel reductions.

    ``Σ_s g·x̂ = rsqrt(σ²+ε)·(Σ_s g·x − μ·Σ_s g)``, so instead of
    materializing ``x̂`` and ``g`` in float32 at activation size (profiled as
    several HBM round trips per BN layer), reduce ``g·x`` and ``g`` straight to
    per-channel sums — two fused einsums with float32 accumulation — and apply
    the affine correction on the tiny [B, C] result."""
    stats_scope = reduce(lambda d, k: d[k], rec["path"], batch_stats)
    mean = stats_scope["mean"].astype(_F32)
    rstd = jax.lax.rsqrt(stats_scope["var"].astype(_F32) + rec["epsilon"])
    # Plain multiply+reduce (NOT einsum): XLA fuses the upcast/multiply chain
    # into the reduction's accumulator — an einsum here lowers to a dot with
    # (b, c) batch dims, whose operand transposes are full HBM round trips.
    axes = tuple(range(1, x.ndim - 1))
    gx = jnp.sum(g.astype(_F32) * x.astype(_F32), axis=axes)
    gs = jnp.sum(g.astype(_F32), axis=axes)
    # A well-shaped [B] zero, not Python 0.0: with use_scale=False and
    # use_bias=False this IS the return value, and the fused path feeds it to
    # custom_vjp as the cotangent of a [B] accumulator — a scalar there is a
    # trace-time shape error.
    contrib = jnp.zeros(x.shape[0], _F32)
    if rec["use_scale"]:
        contrib = contrib + jnp.sum(((gx - mean * gs) * rstd) ** 2, axis=-1)
    if rec["use_bias"]:
        contrib = contrib + jnp.sum(gs * gs, axis=-1)
    return contrib


def _check_covered(records: list[dict], variables) -> None:
    """Every parameter must belong to an intercepted layer — otherwise its
    gradient would be silently missing from the norm (unlike the loud
    NotImplementedErrors for grouped/dilated convs). Conservative by design: a
    parameterized-but-unused module also trips this (its true contribution is
    zero, but we cannot tell "unused" from "missed" here)."""
    covered = {rec["path"] for rec in records}
    for path, _ in jax.tree_util.tree_flatten_with_path(
            variables.get("params", {}))[0]:
        mod_path = tuple(p.key for p in path[:-1])
        if mod_path not in covered:
            raise NotImplementedError(
                f"batched GraNd: parameters at {'/'.join(mod_path)} belong to a "
                "module type the interceptor does not cover (only Conv/Dense/"
                "BatchNorm are); use the grand_vmap score method")


def _refuse_shared_modules(records: list[dict]) -> None:
    """A module applied more than once in a single forward (weight sharing)
    records its path per CALL but sows/taps per PATH — the per-path capture
    keeps only the last call's input while the cotangent sums across calls, so
    both batched algorithms would silently compute the wrong per-layer
    contribution. Same loud-refusal policy as grouped/dilated convs."""
    counts = Counter(rec["path"] for rec in records)
    dupes = sorted("/".join(p) for p, c in counts.items() if c > 1)
    if dupes:
        raise NotImplementedError(
            f"batched GraNd: module(s) applied more than once per forward "
            f"({dupes}): weight sharing needs the gradient SUMMED across "
            "calls before the norm, which the per-path taps cannot express; "
            "use the grand_vmap score method")


def _mega_conv_route(rec: dict, x: jax.Array, g: jax.Array) -> bool:
    """Whether a conv layer takes the megakernel in the fused backward: the
    shared direct-vs-Gram predicate (``_direct_form_ok`` — Gram-regime
    layers would pay the direct form's extra FLOPs), a tiny-F stem gate
    (UNCONDITIONAL here, unlike the two-phase path's STEM_XLA toggle which
    only picks the stem's contraction route: a 25 %-filled megakernel dot
    has no toggle worth bisecting), plus the kernel's own unit-stride/VMEM
    eligibility."""
    from .pallas_kernels import conv_bwd_norm_eligible
    s, f, k = _conv_sfk(rec, x.shape, g.shape)
    if f < 32 or not _direct_form_ok(s, f, k):
        return False
    return conv_bwd_norm_eligible(x.shape, g.shape, rec["kernel_size"],
                                  rec["strides"], x.dtype.itemsize)


def batched_grand_scores_fused(model, variables, image, label, mask,
                               use_pallas: bool = False,
                               megakernel: bool = False) -> jax.Array:
    """Exact per-example GraNd with per-layer contractions fused INTO the
    backward pass. Same math as ``batched_grand_scores`` (verified to the same
    ``vmap(grad)`` tolerance) but instead of differentiating w.r.t. zero output
    perturbations and contracting the returned cotangent pytree afterwards,
    every Conv/Dense/BatchNorm output is wrapped in a ``custom_vjp`` tap whose
    backward (a) passes the cotangent ``g`` through unchanged and (b) emits the
    layer's closed-form grad-norm² contribution as the gradient of a dummy [B]
    accumulator input. ``jax.grad`` w.r.t. the accumulators then yields every
    per-layer contribution from ONE backward in which each contraction sits
    immediately after the op that produced its ``g`` — no second phase, no
    all-layer cotangent tree materialized as grad outputs.

    ``megakernel`` (requires ``use_pallas``): eligible unit-stride convs route
    their taps through ``conv_bwd_grad_norm_sq_pallas`` — the tap's backward
    RETURNS the layer's input cotangent from the same launch that computes the
    contraction (the conv's own XLA backward receives a zero cotangent and
    folds away), so the per-layer bwd→contraction kernel boundary vanishes.
    Ineligible layers (stems, strided/projection convs, Gram-regime stage-4,
    Dense, BatchNorm) keep the plain fused taps."""
    from .scores import cross_entropy  # local import: scores.py imports this module

    # The fused path contracts strictly per layer — the grouping/stacked-BN
    # machinery lives only in the two-phase path. Refuse the combination
    # loudly so a bisect combo can never measure a silently mislabeled
    # program (same policy as _toggle's typo rejection).
    if GROUP_CONV or GROUP_BN or USE_BN_KERNEL:
        raise ValueError(
            "DDT_GRAND_FUSED=1/DDT_GRAND_MEGAKERNEL=1 is incompatible with "
            "DDT_GRAND_GROUP_CONV/GROUP_BN/BN_KERNEL (the fused backward "
            "contracts per layer; grouping exists only in the two-phase path)")
    if megakernel and not use_pallas:
        # The megakernel IS a Pallas kernel: without the Pallas route there is
        # no fused-launch program to measure, and silently falling back would
        # mislabel a bisect combo.
        raise ValueError(
            "DDT_GRAND_MEGAKERNEL=1 requires the Pallas route "
            "(score.use_pallas must not be disabled)")

    records: list[dict] = []
    cap_int = _make_interceptor(records)

    def init_shapes(img):
        with nn.intercept_methods(cap_int):
            model.apply(variables, img, train=False,
                        mutable=["ddt_pert", "ddt_in"])
        return 0
    jax.eval_shape(init_shapes, image)  # abstract: records metadata, no FLOPs
    _check_covered(records, variables)

    batch_stats = variables.get("batch_stats", {})
    _refuse_shared_modules(records)
    rec_by_path = {rec["path"]: rec for rec in records}
    batch = image.shape[0]

    def _contrib(rec: dict, x: jax.Array, g: jax.Array) -> jax.Array:
        if rec["kind"] == "conv":
            return _conv_contrib(rec, x, g, use_pallas)
        if rec["kind"] == "dense":
            return _dense_contrib(rec, x, g)
        return _bn_contrib(rec, x, g, batch_stats)

    def _make_tap(rec: dict):
        @jax.custom_vjp
        def tap(y, x, acc):
            return y

        def fwd(y, x, acc):
            return y, x

        def bwd(x, g):
            # g flows through to the layer output untouched; x's true cotangent
            # arrives via the layer's own backward (the zeros here are
            # algebraically simplified away by XLA).
            return g, jnp.zeros_like(x), _contrib(rec, x, g)

        tap.defvjp(fwd, bwd)
        return tap

    def _make_mega_tap(rec: dict):
        """Conv tap whose backward COMPUTES the layer's input cotangent in the
        same Pallas launch as the contraction (the megakernel). The conv's own
        XLA backward receives a zero output-cotangent and folds away; ``dx``
        is supplied through the x slot instead. Geometry routing happens at
        trace time (shapes are concrete here): ineligible shapes take the
        plain fused tap's math with the weight ignored."""
        from .pallas_kernels import conv_bwd_grad_norm_sq_pallas

        @jax.custom_vjp
        def tap(y, x, wgt, acc):
            return y

        def fwd(y, x, wgt, acc):
            return y, (x, wgt)

        def bwd(res, g):
            x, wgt = res
            if _mega_conv_route(rec, x, g):
                pad = _explicit_padding(rec["padding"], x, g, rec)
                dx, contrib = conv_bwd_grad_norm_sq_pallas(
                    x, g, wgt, tuple(rec["kernel_size"]), pad,
                    use_bias=rec["use_bias"])
                return jnp.zeros_like(g), dx, jnp.zeros_like(wgt), contrib
            return (g, jnp.zeros_like(x), jnp.zeros_like(wgt),
                    _contrib(rec, x, g))

        tap.defvjp(fwd, bwd)
        return tap

    mega_paths = ({path for path, rec in rec_by_path.items()
                   if rec["kind"] == "conv"} if megakernel else set())
    taps = {path: (_make_mega_tap(rec) if path in mega_paths
                   else _make_tap(rec))
            for path, rec in rec_by_path.items()}
    # The interceptor runs inside model.apply, so the traced accumulators reach
    # it through this cell (rebound per loss_fn call).
    acc_cell: dict = {}

    def fused_interceptor(next_fun, args, kwargs, context):
        mod = context.module
        if (context.method_name != "__call__"
                or not isinstance(mod, (nn.Conv, nn.Dense, nn.BatchNorm))
                or mod.scope is None):
            return next_fun(*args, **kwargs)
        path = tuple(mod.path)
        y = next_fun(*args, **kwargs)
        if path in mega_paths:
            wgt = _leaf(variables["params"], path, "kernel")
            return taps[path](y, args[0], wgt, acc_cell[path])
        return taps[path](y, args[0], acc_cell[path])

    def loss_fn(accs):
        acc_cell.clear()
        acc_cell.update(accs)
        with nn.intercept_methods(fused_interceptor):
            logits = model.apply(variables, image, train=False)
        return jnp.sum(cross_entropy(logits, label) * mask)

    acc0 = {path: jnp.zeros((batch,), _F32) for path in taps}
    contribs = jax.grad(loss_fn)(acc0)
    norm_sq = jnp.zeros(batch, _F32)
    for c in contribs.values():
        norm_sq = norm_sq + c
    return jnp.sqrt(norm_sq) * mask


def batched_grand_scores(model, variables, image, label, mask,
                         use_pallas: bool = False) -> jax.Array:
    """Exact per-example GraNd over all parameters, fully batched. [B] <- batch.

    ``use_pallas`` routes large-S conv layers through the fused
    ``conv_grad_norm_sq_pallas`` kernel (no patch/M materialization in HBM)."""
    from .scores import cross_entropy  # local import: scores.py imports this module

    records: list[dict] = []
    cap_int = _make_interceptor(records)
    run_int = _make_interceptor(None)

    def apply_fn(perts, interceptor, img):
        with nn.intercept_methods(interceptor):
            return model.apply({**variables, "ddt_pert": perts}, img,
                               train=False, mutable=["ddt_in"])

    # Shape pass (abstract — no FLOPs): records layer metadata and yields the
    # perturbation-tree structure, i.e. every layer's output shape.
    def init_shapes(img):
        with nn.intercept_methods(cap_int):
            _, mut = model.apply(variables, img, train=False,
                                 mutable=["ddt_pert", "ddt_in"])
        return mut["ddt_pert"]
    pert_shapes = jax.eval_shape(init_shapes, image)
    perts0 = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), pert_shapes)

    def loss_fn(perts):
        logits, mut = apply_fn(perts, run_int, image)
        loss = jnp.sum(cross_entropy(logits, label) * mask)
        return loss, mut["ddt_in"]

    _check_covered(records, variables)
    _refuse_shared_modules(records)

    cotangents, captures = jax.grad(loss_fn, has_aux=True)(perts0)

    batch_stats = variables.get("batch_stats", {})
    norm_sq = jnp.zeros(image.shape[0], _F32)
    # Same-geometry layers are GROUPED into one kernel launch (batch-concat):
    # a ResNet's stages repeat identical conv/BN shapes 3-5×, and per-launch
    # overhead (dispatch + layout transitions around each Pallas call) was
    # profiled at ~⅓ of the round-3 scoring pass. Conv groups concatenate
    # along the batch; BN groups additionally stack per-layer statistics
    # (see _bn_group_contrib). Summation order changes only across layers
    # (f32 accumulation, same magnitudes — well below score tolerance).
    conv_groups: dict[tuple, list] = {}
    bn_groups: dict[tuple, list] = {}
    for rec in records:
        x = _leaf(captures, rec["path"], "x")   # sow reduce_fn stores the raw array
        g = _leaf(cotangents, rec["path"], "y")
        if rec["kind"] == "conv":
            key = (x.shape, g.shape, rec["kernel_size"], rec["strides"],
                   rec["padding"], rec["use_bias"],
                   rec["path"] if not GROUP_CONV else None)
            conv_groups.setdefault(key, []).append((rec, x, g))
        elif rec["kind"] == "dense":
            norm_sq = norm_sq + _dense_contrib(rec, x, g)
        else:
            key = (x.shape, rec["epsilon"], rec["use_scale"], rec["use_bias"],
                   rec["path"] if not GROUP_BN else None)
            bn_groups.setdefault(key, []).append((rec, x, g))
    for items in conv_groups.values():
        rec = items[0][0]
        if len(items) == 1:
            norm_sq = norm_sq + _conv_contrib(rec, items[0][1], items[0][2],
                                              use_pallas)
        else:
            xs = jnp.concatenate([x for _, x, _ in items], axis=0)
            gs = jnp.concatenate([g for _, _, g in items], axis=0)
            contrib = _conv_contrib(rec, xs, gs, use_pallas)
            norm_sq = norm_sq + jnp.sum(
                contrib.reshape(len(items), image.shape[0]), axis=0)
    for items in bn_groups.values():
        norm_sq = norm_sq + _bn_group_contrib(items, batch_stats, use_pallas)
    return jnp.sqrt(norm_sq) * mask
