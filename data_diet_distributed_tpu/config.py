"""Typed configuration system.

Replaces the reference's flat ``config.yaml`` + three duplicated ``load_config`` copies
(``train.py:13-16``, ``ddp.py:18-21``, ``ddp_new.py:102-105``) and its argparse bypasses
(``train.py:19-23``) with one validated dataclass tree, loadable from YAML and overridable
from the command line with ``dotted.key=value`` pairs. Dead reference keys
(``sparsity`` and ``batch_size_scores`` in ``config.yaml:3-4`` were never read) do not
exist here; every field is consumed.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, fields
from typing import Any

import yaml


@dataclass
class DataConfig:
    """Dataset selection and host-side pipeline knobs (reference: ``data/loader.py``)."""

    # cifar10 | cifar100 | synthetic | synthetic_imagenet | npz (bring-your-own
    # train.npz/test.npz in data_dir — the ImageNet-subset path)
    dataset: str = "cifar10"
    data_dir: str = "./data"          # where CIFAR python batches / npz files live
    batch_size: int = 128             # global batch size (reference: config.yaml:7)
    eval_batch_size: int = 500        # reference hardcodes 100 (data/loader.py:41)
    synthetic_size: int = 2048        # train-set size for the synthetic datasets
    # Per-pixel noise std for the synthetic datasets (class templates have std
    # 0.5). The default is easily separable; raise it to make the task hard
    # enough that pruning visibly costs accuracy (e2e sweep demonstrations).
    synthetic_noise: float = 0.4
    # clusters > 1 makes each synthetic class a Zipf-weighted mixture of that
    # many templates — a heavy-tailed task whose sample complexity is real:
    # rare clusters are hard informative examples (the regime pruning is FOR).
    # 1 = the historical single-template stream, bit-identical.
    synthetic_clusters: int = 1
    shuffle_each_epoch: bool = True   # reference bug 2.4.6: DDP reshuffle never happened
    # On-device training augmentation (random crop + flip inside the jitted
    # train step — data/augment.py). The reference trains un-augmented
    # (data/loader.py:8-11), so the default preserves its semantics.
    augment: bool = False
    crop_pad: int = 4                 # random-crop padding when augment=true
    # Horizontal flip as part of augment=true. Off for orientation-sensitive
    # datasets (digits/characters via the npz path) where mirroring changes
    # example semantics.
    flip: bool = True
    # --- streaming data plane (data/sharded.py + data/pipeline.py) ----------
    # auto | streaming | resident. "auto" keeps the residency heuristics
    # (resident engines when the dataset fits, per-step streaming otherwise);
    # "streaming" forces the streaming plane — prefetched chunk blocks /
    # per-step prefetch, nothing dataset-sized held in HBM (bit-identical to
    # resident, pinned); "resident" requires residency and errors where it
    # cannot be honored (multi-host, oversized datasets).
    data_plane: str = "auto"
    # Host→device prefetch depth for the streaming plane: the background
    # assembler keeps up to this many blocks/batches decoded, normalized, and
    # uploaded ahead of the dispatch loop. 0 = synchronous assembly (the A/B
    # baseline bench.py --data-plane measures against).
    prefetch_depth: int = 2
    # Decoded-shard LRU budget for dataset="sharded" (bytes): a hard host-RAM
    # bound — exceeding it evicts the coldest decoded shard, never OOMs.
    host_cache_bytes: int = 1 << 30
    # Hardened shard reads (data/sharded.py): every read is digest-verified
    # against the manifest; a failed read (transient EIO/ENOENT, or a torn
    # shard's digest mismatch) is retried up to read_retries times with
    # exponential backoff starting at read_backoff_s. Exhausting the budget
    # QUARANTINES the shard (loud data_fault + shard_quarantine records) and
    # aborts the pass with a typed ShardReadError — garbage bytes never
    # become rows, so they can never become silently-wrong prune decisions.
    read_retries: int = 2
    read_backoff_s: float = 0.05
    # Opt-in degraded mode: a quarantined shard's rows are served as zero
    # placeholders, DROPPED from the prune decision, and the drop recorded
    # in the prune-provenance sidecar (auditable degraded scoring instead of
    # an abort). Off by default — aborting is the safe behavior.
    skip_quarantined: bool = False

    @property
    def num_classes(self) -> int | None:
        """Class count when statically known; None for npz (inferred at load)."""
        return {"cifar10": 10, "cifar100": 100, "synthetic": 10,
                "synthetic_imagenet": 100, "npz": None, "sharded": None}[self.dataset]


@dataclass
class ModelConfig:
    """Model zoo selection (reference: ``models/resnet.py:100-117`` factories)."""

    arch: str = "resnet18"   # resnet18/34/50/101/152 | wideresnet28_10
    num_classes: int = 10
    # ResNet input geometry: "cifar" (3x3/s1 stem, no pool — the reference's,
    # models/resnet.py:71-73) or "imagenet" (7x7/s2 + 3x3/s2 max-pool).
    stem: str = "cifar"
    # Rematerialize block activations in backward passes (jax.checkpoint):
    # ~1 extra forward of FLOPs for O(depth) less activation HBM — for deep
    # models / big batches. Parameter trees are identical either way.
    remat: bool = False


@dataclass
class OptimConfig:
    """SGD + momentum + weight decay + cosine schedule (reference: ``train.py:76-77``)."""

    lr: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 5e-4
    nesterov: bool = False
    # Cosine T_max in epochs; reference sets CosineAnnealingLR(T_max=num_epochs)
    # (train.py:77) but train_sparse.py uses 200 with 20 epochs (train_sparse.py:39-40).
    cosine_t_max_epochs: int | None = None  # None -> num_epochs
    # Linear LR warmup epochs before the cosine (large-batch recipe; the
    # reference has none, so 0 preserves its schedule).
    warmup_epochs: int = 0
    grad_clip_norm: float | None = None


@dataclass
class ScoreConfig:
    """Per-example scoring pass (reference: ``get_scores_and_prune.py``)."""

    # el2n | margin | grand | grand_vmap | grand_last_layer | forgetting | aum.
    # "grand" is full-parameter GraNd via the batched exact algorithm
    # (ops/grand_batched.py) in eval mode; "grand_vmap" forces the naive
    # vmap(grad) path (cross-checks, exotic layers); "margin" is the
    # uncertainty-margin baseline max_{k≠y} p_k − p_y (higher = harder);
    # "forgetting" counts forgetting events across score.pretrain_epochs of
    # training (Toneva et al. 2019, ops/forgetting.py); "aum" averages the
    # probability margin across the same trajectory (area-under-margin,
    # Pleiss et al. 2020, sign-flipped so higher = harder).
    method: str = "el2n"
    # Which checkpoint feeds the scoring pass. The reference hard-codes epoch 19
    # (train.py:61, ddp.py:72); here it is a knob.
    score_ckpt_step: int | None = None    # None -> latest available checkpoint
    # Dense epochs to train each scoring seed before scoring (0 = score at init,
    # i.e. GraNd-at-initialization). Replaces the reference's fixed epoch-19 ckpt.
    pretrain_epochs: int = 2
    seeds: tuple[int, ...] = (0,)         # multi-seed averaging (paper uses 10 seeds)
    batch_size: int = 512                 # scoring is forward-only -> can run larger
    grand_chunk: int = 32                 # vmap(grad) chunk size per device for full GraNd
    # The reference accidentally scores in train mode with grads on (§2.4.1 of SURVEY.md);
    # we score in eval mode by default but keep the switch for A/B parity studies.
    eval_mode: bool = True
    # Fused Pallas score kernels: None = auto (on for TPU backends, off elsewhere).
    use_pallas: bool | None = None
    # Chunked score engine: K score batches compiled into ONE dispatch over
    # the pre-batched pre-sharded resident blocks (ops/scores.make_score_chunk
    # scanning ops/scoring.ScoreResident) — bit-identical to the per-batch
    # path.
    # None = auto (the whole epoch per dispatch on resident single-process
    # passes, clamped to ops/scoring.MAX_SCORE_CHUNK_STEPS); 0/1 = per-batch.
    chunk_steps: int | None = None
    # Reuse previously-computed scores from a saved npz (as written by the
    # run/score/sweep commands) instead of scoring: prune/retrain experiments
    # then pay zero scoring cost. The npz's indices are joined to the dataset
    # by global id, so subsets/reorderings are safe; a mismatch refuses loudly.
    scores_npz: str | None = None


@dataclass
class PruneConfig:
    """Keep-hardest subset selection (reference: ``get_scores_and_prune.py:22-27``)."""

    sparsity: float = 0.5      # fraction of the train set to DROP
    keep: str = "hardest"      # hardest | easiest | random (paper ablations)
    # Apportion the kept budget per class proportionally (keep-hardest skews
    # class balance at high sparsity — Paul et al. 2021 §5).
    class_balance: bool = False
    # ``cli sweep``: retrain once per listed sparsity from ONE shared scoring
    # pass (scores are sparsity-independent). The BASELINE WRN-28-10 sweep
    # {0.3, 0.5, 0.7} is three reference runs, re-scoring each time; here it
    # is one scoring pass + three retrains.
    sweep: tuple[float, ...] = ()


@dataclass
class TrainConfig:
    """Epoch-loop driver (reference: ``train.py:80-83`` — which ran num_epochs+1 epochs;
    here ``num_epochs`` means exactly that many)."""

    num_epochs: int = 10
    seed: int = 0
    eval_every: int = 1
    checkpoint_every: int = 5      # reference saved every epoch unconditionally (§2.4.9)
    checkpoint_dir: str = "./checkpoints"
    keep_checkpoints: int = 20
    resume: bool = False           # true resume (params+opt_state+step); reference had none
    # Restart-based failure recovery (SURVEY §5.3: the reference has none — a crashed
    # worker fails the whole job): on an exception mid-fit, re-enter from the latest
    # checkpoint up to this many times.
    auto_resume_retries: int = 0
    half_precision: bool = True    # bfloat16 compute on TPU, fp32 params
    # Upload train/test sets to HBM once and gather batches on device (epoch
    # host->device traffic becomes one index permutation). None = auto: on for
    # single-process meshes when the dataset fits data/pipeline.RESIDENT_MAX_BYTES.
    device_resident_data: bool | None = None
    # Chunked execution engine: compile K consecutive train steps (resident
    # gather included) into ONE dispatch (train/steps.make_train_chunk) — the
    # per-step dispatch tax (~25 ms on relay-attached hosts) is paid once per
    # chunk. None = auto: on (train/loop.DEFAULT_CHUNK_STEPS) for
    # single-process device-resident runs, per-step otherwise (streaming,
    # multi-host consensus, and step-targeted fault injection always use the
    # per-step path); 0/1 = force per-step; K>1 = requested size, clamped to
    # the epoch length and train/loop.MAX_CHUNK_STEPS. Results are
    # bit-identical either way (pinned by tests/test_chunked.py); resilience
    # hooks (watchdog beat, preemption poll) run at chunk boundaries, so a
    # SIGTERM is honored within at most one chunk of steps.
    chunk_steps: int | None = None
    log_every_steps: int = 50


@dataclass
class MeshConfig:
    """Device-mesh geometry. The reference hard-codes world sizes 6 / 4
    (``ddp.py:179``, ``ddp_new.py:264``); here the mesh is derived from visible devices
    unless pinned. Axes: ``data`` (batch sharding; the reference's only parallelism) and
    ``model`` (reserved tensor-parallel axis for the wide-classifier configs)."""

    data_axis: int | None = None     # None -> n_devices // model_axis
    model_axis: int = 1
    # ZeRO-1-style optimizer-state sharding over the data axis: each DP rank
    # holds 1/data_axis of the momentum buffers (params stay replicated; XLA
    # gathers the sharded slots where the update needs them). Off by default —
    # it trades one all-gather per step for optimizer memory, which only pays
    # once params are a meaningful fraction of HBM.
    shard_opt_state: bool = False
    # Cross-replica SHARDED WEIGHT UPDATE (arXiv 2004.13336, the ZeRO-on-TPU
    # recipe): params AND optimizer slots live data-axis sharded between
    # steps, gradients reduce-SCATTER (not all-reduce) onto the data axis,
    # each replica updates only its parameter shard, and the forward
    # all-gathers weights at use — where the latency-hiding scheduler can
    # overlap both collectives against compute (parallel.overlap). Implies
    # shard_opt_state. Bit-identical to the replicated update on CPU meshes
    # (pinned); None = auto: armed by DDT_SHARDED_UPDATE=1 pending the
    # on-chip bisection, like the GraNd megakernel gate.
    shard_weight_update: bool | None = None
    # Multi-host: call jax.distributed.initialize() before device queries.
    multihost: bool = False
    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None


@dataclass
class OverlapConfig:
    """XLA latency-hiding / async-collective flags (``parallel/overlap.py``)
    that let the compiler overlap the sharded update's reduce-scatter and
    weight all-gather against backward/forward compute.

    Flags go into ``XLA_FLAGS`` and must land BEFORE backend init (the CLI
    applies them right before ``initialize_multihost``); they are TPU-backend
    flags, so ``enabled=None`` (auto) applies them only when the target
    backend is TPU — on CPU lanes, or once a backend is already initialized,
    overlap cannot engage and the apply degrades to a no-op with one
    warning."""

    enabled: bool | None = None      # None = auto: TPU backends only
    latency_hiding_scheduler: bool = True
    async_all_gather: bool = True
    async_reduce_scatter: bool = True
    async_all_reduce: bool = True
    async_collective_permute: bool = True
    # Extra raw XLA flags appended verbatim (operator escape hatch).
    extra_flags: tuple[str, ...] = ()


@dataclass
class ParallelConfig:
    """Communication-layer knobs that are not mesh GEOMETRY (which stays in
    ``mesh``): today, the comm/compute overlap block."""

    overlap: OverlapConfig = field(default_factory=OverlapConfig)


@dataclass
class CheckpointConfig:
    """Multi-tier checkpointing (``checkpoint.py`` LocalTier): a fast
    per-rank LOCAL-disk save at step cadence, promoted to the durable tier
    by a background thread with digest verification — pod-scale state never
    stalls the step on durable-storage latency. The durable tier
    (``<train.checkpoint_dir>_tiered``) is what restore/consensus trust; a
    step counts as restorable only once EVERY rank's shard is promoted and
    digest-verified. Preemption drains in-flight promotions before exit 75."""

    local_tier: bool = False
    # Per-rank local (fast) tier ROOT; None -> <checkpoint_dir>_local.
    # Point it at genuinely local disk on real pods — it is namespaced by
    # the checkpoint directory's identity (checkpoint.local_tier_dir), so
    # every job on a host may share one configured root without their
    # scratch steps colliding.
    local_dir: str | None = None
    promote: bool = True             # background promotion to the durable tier
    drain_timeout_s: float = 120.0   # preemption-path bound on the drain
    # Artificial promotion delay (seconds) — test/ops hook so drills can pin
    # a SIGTERM landing while a save is in flight.
    promote_delay_s: float = 0.0


@dataclass
class ElasticConfig:
    """Elastic pod supervision (``resilience/elastic.py``): survive host
    loss mid-run, grow back on host join. With ``enabled=true`` the CLI
    becomes a bounded restart supervisor: it spawns ``world`` worker
    processes of the same invocation, and on a non-graceful worker death
    (SIGKILL/OOM — the survivors exit retriably via watchdog/consensus
    poison) relaunches the job on the surviving world size with
    ``train.resume=true`` — the stage manifest + multi-tier checkpoints
    re-enter at the exact stage, with params/opt-state shards remapped to
    the new device count at restore. A join request
    (``elastic.request_join`` / the ``rejoin_after_stage`` injection) grows
    the pod back at the next stage boundary. Every decision is an
    ``{"kind": "elastic_event"}`` record."""

    enabled: bool = False
    # Initial worker count; None -> mesh.num_processes or 1. The CLI
    # supervisor launches all ranks on THIS host (CPU pods, single-host
    # multi-chip); a per-host launcher reuses ElasticSupervisor with its
    # own spawn hook on real multi-host pods.
    world: int | None = None
    min_world: int = 1               # never shrink below this many ranks
    max_world: int | None = None     # grow ceiling; None -> initial world
    max_restarts: int = 5            # failure-relaunch budget (grows are free)
    backoff_s: float = 2.0           # exponential between failure relaunches
    # After the first non-graceful death in an attempt, how long surviving
    # children get to exit on their own (their watchdog/poison escalation)
    # before the supervisor terminates them.
    reap_timeout_s: float = 60.0
    # Heartbeat age past which a rank counts dead for survivor naming.
    heartbeat_stale_s: float = 30.0
    # Relaunch (with resume) after a clean preemption exit 75. True fits
    # the supervised-pod model (an injected/per-worker SIGTERM is the
    # worker's eviction, not the supervisor's); set false where 75 must
    # propagate to an outer scheduler.
    resume_preempted: bool = True


#: Checkpoint-based score methods the serving layer can hold warm
#: (trajectory methods score a training run, not a checkpoint — they cannot
#: answer a request). ONE definition: ``Config.validate`` and the serve
#: engine's method dispatch both read it.
SERVABLE_METHODS = ("el2n", "margin", "grand", "grand_vmap",
                    "grand_last_layer")


@dataclass
class ServeConfig:
    """Scoring-as-a-service (``serve/``): a long-lived process that keeps
    compiled score programs and dataset residents warm on the mesh and
    answers streaming HTTP requests — ``POST /v1/score`` (score a batch of
    examples), ``POST /v1/rank`` (re-rank a slice), ``GET /v1/topk``
    (top-k hardest, streamed), plus the obs stack's /healthz /metrics
    /status. Booted by ``cli serve``; requests coalesce into chunked score
    dispatches (``serve/batcher.py``) with admission control (429 +
    Retry-After past ``max_queue``) and weighted round-robin fairness
    across tenants. SIGTERM drains in-flight requests bounded by
    ``drain_timeout_s`` and exits 75 (the preemption contract)."""

    port: int = 0                    # 0 = auto-pick; logged as obs_server
    host: str = "127.0.0.1"
    # Default tenant name the CLI registers; None -> data.dataset.
    tenant: str | None = None
    # Methods warmed (compiled + resident-scored) at boot; () -> the
    # configured score.method only. Requests may still name any registry
    # method — unwarmed ones pay their compile on first use.
    methods: tuple[str, ...] = ()
    # Request-batch geometry (the compiled program's B); None ->
    # score.batch_size. Requests pad to this tile (row-0 tail discipline).
    batch_size: int | None = None
    # Per-tenant pending-request cap: a submit past it is rejected with
    # 429 + Retry-After (admission control, never an unbounded queue).
    max_queue: int = 64
    retry_after_s: float = 1.0       # the 429 Retry-After hint
    # Deadline-bounded coalescing window: a partial batch dispatches at most
    # this long after its oldest request arrived (a full batch never waits).
    coalesce_ms: float = 5.0
    # Per-request completion bound inside the service (queue + dispatch).
    request_timeout_s: float = 60.0
    # SIGTERM drain: stop admission, finish in-flight work, bounded.
    drain_timeout_s: float = 30.0
    # serve_stats record + serve-SLO evaluation cadence in the serve loop.
    stats_every_s: float = 10.0
    # Score the registered dataset for every serve.methods method at boot
    # (warms the compiled programs AND the resident top-k/rank answers).
    warm: bool = True
    # Per-request {"kind": "serve_request"} records (tenant/method/n/walls).
    # Disable for genuinely heavy traffic; serve_stats aggregates remain.
    request_log: bool = True
    # --- serving fleet (serve/fleet.py + serve/router.py) ---------------
    # replicas > 1 turns `cli serve` into a ServeFleet supervisor: N serve
    # replicas as child processes (each its own mesh + port), fronted by a
    # health-aware router on `port`/`router_port`. 1 = single process
    # (the PR-13 behaviour, unchanged).
    replicas: int = 1
    # Router's public port (0 = auto-pick; logged as obs_server). The
    # per-replica backend ports are always auto-picked by the fleet.
    router_port: int = 0
    # Serve-side watchdog: a score dispatch in flight longer than this
    # makes /healthz critical (wedged dispatcher) -> the router stops
    # routing there and the fleet drains + respawns the replica.
    # None = watchdog off.
    dispatch_stall_s: float | None = 30.0
    # Zero-downtime refresh: poll the refresh checkpoint dir for a newer
    # step this often and roll it across replicas. None = manual only
    # (POST /v1/refresh).
    refresh_poll_s: float | None = None
    # Checkpoint dir refreshes restore from; None -> train.checkpoint_dir.
    # Digest-verified (CheckpointManager.restore_checked) before install.
    refresh_from: str | None = None
    # Router retry budget for idempotent requests (requests carrying an
    # Idempotency-Key header) across replicas, within request_timeout_s.
    route_retries: int = 2
    # Per-replica circuit breaker: this many consecutive transport
    # failures open the circuit; after breaker_reset_s one probe request
    # is let through (half-open) and a success closes it.
    breaker_failures: int = 3
    breaker_reset_s: float = 2.0
    # Tail-latency hedging: an idempotent request still unanswered after
    # this many ms is duplicated to a second replica, first answer wins
    # (the loser's connection is closed). None = off.
    hedge_ms: float | None = None
    # Fleet health-poll cadence (per-replica /healthz) in seconds.
    health_poll_s: float = 0.5
    # Router idempotency-replay cache entries (bounded LRU keyed by the
    # Idempotency-Key header; a retried request replays the cached
    # response instead of double-dispatching).
    idempotency_cache: int = 256
    # --- cross-host placement (RemoteReplicaBackend) --------------------
    # Per-slot host list: replica i runs on hosts[i % len(hosts)], spawned
    # through the remote_launch template and dialed at that host. () = all
    # replicas local (the default backend). Every listed host goes through
    # the template — list "127.0.0.1" to exercise the remote path locally.
    hosts: tuple[str, ...] = ()
    # Remote-launch command template, formatted with {host} — the same
    # worker-launch plumbing the multihost tests use. The template's argv
    # prefix executes a command on the host (e.g. "ssh -o BatchMode=yes
    # {host}"); the child's env rides behind it via `env K=V ...`. The
    # launcher process is supervised exactly like a local child: its
    # lifetime is the remote replica's lifetime (ssh semantics). Required
    # when hosts is non-empty.
    remote_launch: str | None = None
    # --- SLO-driven autoscaler (serve/fleet.py Autoscaler) --------------
    # Fleet-size bounds: setting max_replicas turns the autoscaler on
    # (min_replicas defaults to serve.replicas). Scaling signals are the
    # same ones check_fleet/check_serve judge: router tick p95 vs
    # obs.slo_fleet_p95_ms, summed replica queue depth vs
    # obs.slo_serve_queue_depth, reject fraction vs
    # obs.slo_serve_reject_frac. Both null = static fleet (PR-15).
    min_replicas: int | None = None
    max_replicas: int | None = None
    # Hysteresis: consecutive violating stats ticks before a scale-up,
    # consecutive headroom (idle / comfortably-under-floor) ticks before a
    # scale-down, and the cooldown wall between any two actions.
    scale_up_after: int = 2
    scale_down_after: int = 5
    scale_cooldown_s: float = 10.0
    # --- partition probation (dead process vs dead network) -------------
    # Consecutive unreachable health polls — process still alive, replica
    # previously seen healthy — that classify as a network partition. A
    # partitioned replica is quarantined (breaker + unroutable) and
    # re-probed with backoff; it never spends restart budget.
    partition_after_misses: int = 3
    # Probation re-probe backoff: starts at probe_backoff_s, doubles per
    # missed probe, capped at probe_backoff_max_s.
    probe_backoff_s: float = 0.5
    probe_backoff_max_s: float = 8.0
    # --- canary-first refresh (serve/router.py roll) --------------------
    # Routed requests the first-rolled (canary) replica must answer before
    # the roll continues to the rest of the fleet; the roll aborts and the
    # canary is rolled BACK to the prior model when its window error rate
    # or p95 regresses past the fleet SLO floors (obs.slo_fleet_p95_ms /
    # obs.slo_serve_reject_frac). None = no canary hold (the PR-15 roll).
    canary_requests: int | None = None
    # Canary-hold wall bound; zero routed traffic inside it is judged
    # inconclusive and the roll proceeds (recorded as such).
    canary_timeout_s: float = 30.0
    # --- request tracing (obs/reqtrace.py) ------------------------------
    # Head-sampling fraction for HEALTHY traffic's {"kind":"serve_trace"}
    # records. The keep/drop decision hashes the trace id, so the router
    # and every replica independently reach the same answer for the same
    # request (no coordination header needed on the happy path). Failed,
    # slow, retried, hedged, and replayed requests are ALWAYS kept
    # regardless of this knob (tail-biased retention). 0.0 = tail only,
    # 1.0 = every request.
    trace_sample_frac: float = 0.02
    # Wall-time threshold (ms) past which a request counts as "slow" and
    # its trace is always kept. None -> obs.slo_serve_p95_ms when that
    # SLO is armed, else 250 ms.
    trace_slow_ms: float | None = None


@dataclass
class ResilienceConfig:
    """Fault-tolerance layer (``resilience/``): watchdog, preemption handling,
    checkpoint integrity, NaN sentinel. The reference has none of it — a hung
    or evicted worker lost the whole job (SURVEY §5.3)."""

    # Heartbeat deadline over training progress units: each step, the epoch
    # metrics fetch, the eval pass, the epoch hook, and the checkpoint save
    # each get a fresh deadline; a unit that makes no host-side progress for
    # this long raises a retriable WatchdogTimeout instead of hanging forever.
    # None = off (the deadline must be sized to the slowest legitimate unit,
    # compile and a full eval pass included — no universal default).
    step_timeout_s: float | None = None
    # SIGTERM/SIGINT -> final synchronous checkpoint -> Preempted (CLI exit
    # 75); rerun with train.resume=true to continue.
    preemption: bool = True
    # Verify restored checkpoints against their save-time manifest
    # (step/shape/dtype/finite-ness) and fall back to the newest earlier
    # durable step when the latest is corrupt.
    verify_restore: bool = True
    # Raise on NaN/inf epoch loss BEFORE the diverged state is checkpointed...
    nan_check: bool = True
    # ...then roll back to the last good checkpoint and retry with
    # lr *= nan_lr_factor, up to nan_retry_budget times (its own budget:
    # replaying the same LR would diverge identically, so divergence retries
    # are not generic crash retries).
    nan_retry_budget: int = 1
    nan_lr_factor: float = 0.5
    # Subprocess-bounded `jax.devices()` probe with retry + exponential
    # backoff BEFORE the in-process backend init — converts the device-claim
    # wedge into a parseable failure. Off by default for the CLI (CPU/test
    # runs skip the subprocess); bench.py always probes unless --no-probe.
    init_probe: bool = False
    probe_attempts: int = 3
    probe_timeout_s: float = 150.0
    probe_backoff_s: float = 20.0
    # Multi-host fault consensus (resilience/consensus.py; no-op
    # single-process): preemption flags OR-reduced so every rank writes the
    # same final checkpoint and exits 75 together; the NaN verdict globally
    # agreed; restore pinned to the newest step EVERY rank verified; watchdog
    # firings broadcast through a poison side-channel so peers abort instead
    # of hanging in a dead collective.
    consensus: bool = True
    # Preemption/peer-poison poll cadence in steps: 1 = every step (tightest
    # agreement, one tiny allgather per step); raise it to amortize on
    # meshes where per-step host collectives measurably cost.
    consensus_poll_steps: int = 1
    # After a watchdog firing (own or peer-poisoned), a rank whose main
    # thread is still wedged in a collective this much later exits with the
    # retriable status 69 — bounded abort instead of unbounded hang.
    consensus_grace_s: float = 15.0
    # Poison side-channel directory; None -> <train.checkpoint_dir>_sidechannel
    # (must be on a filesystem every rank sees, like the checkpoint dir).
    sidechannel_dir: str | None = None
    # Durable stage manifest + per-seed score partials (resilience/stages.py):
    # an interrupted run/sweep re-enters at the exact pipeline stage — scores
    # resume from the first incomplete seed, a mid-retrain preemption resumes
    # from the retrain's own checkpoints, completed sweep levels are skipped.
    stage_resume: bool = True


@dataclass
class ObsConfig:
    """Observability (reference: prints + ``ddp_new.py:21-99`` sidecar monitor).

    The unified layer (``obs/``): hierarchical trace spans (Chrome-trace
    JSON), a metrics registry (counters/gauges/streaming histograms,
    snapshotted into the JSONL and optionally a Prometheus textfile),
    per-rank heartbeat files, and a per-rank fault flight recorder. All four
    are wired by the CLI's ``ObsSession``; library code reaches them through
    module-level no-op-until-installed helpers, so running without the
    session costs one ``is None`` check per hook."""

    metrics_path: str = "./metrics.jsonl"
    monitor: bool = False            # 1 Hz host/device utilization sampling thread
    monitor_path: str = "./utilization.jsonl"
    profile_dir: str | None = None   # jax.profiler trace output directory
    plots_dir: str | None = None     # post-run PNGs (reference: ddp_new.py:71-99)
    # Hierarchical span tracing (obs/tracing.py): run -> stage -> seed ->
    # epoch -> chunk/eval spans exported as Chrome-trace/Perfetto JSON.
    # None path -> trace.json next to the metrics JSONL (per-rank suffix
    # under multi-host). Summarize with tools/trace_report.py or open in
    # https://ui.perfetto.dev. Distinct from profile_dir (XLA-level op
    # profiling) — spans are pipeline-grained and always cheap.
    trace: bool = True
    trace_path: str | None = None
    # Metrics registry snapshots: a {"kind": "metrics"} JSONL record at most
    # every this-many seconds (checked at epoch boundaries; 0 disables), and
    # a Prometheus-style textfile for external scrapers when prom_path is
    # set (refreshed on each snapshot and at session exit).
    snapshot_every_s: float = 60.0
    prom_path: str | None = None
    # Per-rank heartbeat files (obs/heartbeat.py): step/epoch/stage/last-
    # progress JSON, atomically rewritten on training progress, throttled to
    # one write per heartbeat_interval_s on the per-step path. Read by the
    # watchdog (timeout messages name the stalest rank) and the consensus
    # poison path. None dir -> <train.checkpoint_dir>_heartbeats (must be a
    # filesystem every rank sees, like the checkpoint dir).
    heartbeat: bool = True
    heartbeat_dir: str | None = None
    heartbeat_interval_s: float = 0.5
    # Fault flight recorder (obs/flightrec.py): bounded ring of the last
    # flightrec_capacity events on EVERY rank, dumped to
    # <dir>/flightrec_rank<k>.json from the fault paths (watchdog fire, NaN
    # sentinel, preemption, step exception). None dir -> next to the
    # metrics JSONL.
    flightrec: bool = True
    flightrec_capacity: int = 256
    flightrec_dir: str | None = None
    # XLA compiled-program introspection (obs/xla.py): once per (jitted
    # factory, geometry), harvest cost_analysis (flops, bytes accessed) +
    # memory_analysis (arg/output/temp/peak-estimate bytes) + compile
    # wall-time into {"kind": "xla_program"} JSONL records and xla_* registry
    # gauges, derive MFU at epoch boundaries, and poll device.memory_stats()
    # watermarks at chunk boundaries (hbm_* gauges + a flight-recorder trail
    # on peak jumps >= hbm_jump_frac). Backends returning empty/partial
    # analysis degrade to null fields — never a crash.
    xla_introspect: bool = True
    hbm_jump_frac: float = 0.10
    # profile_dir's automatic capture (obs/profiler.ProfileWindow): a
    # steady-state window of this many chunk dispatches per pipeline stage
    # (the compile epoch is skipped), one capture per stage tag.
    profile_window_chunks: int = 8
    # Score Observatory (obs/scoreboard.py): per-(method, seed) score
    # distribution records ({"kind": "score_stats"}: moments/percentiles/
    # bounded histogram/NaN counts + score_* gauges), cross-seed rank
    # stability after multi-seed passes ({"kind": "score_stability"}:
    # pairwise Spearman ρ, mean-vs-seed ρ, overlap@k at the configured keep
    # fractions, surfaced in run_summary), and the prune stage's
    # {"kind": "prune_decision"} record next to the provenance sidecar
    # manifest. Host math once per SEED pass over already-fetched arrays —
    # no extra device dispatches.
    score_telemetry: bool = True
    # Fixed bin count of the histogram embedded in each score_stats record
    # (bounded by construction regardless of dataset size).
    score_hist_bins: int = 32
    # Append-only perf-history ledger (JSONL; tools/perf_sentry.py compares
    # runs across time): every run appends one {"kind": "perf_history"}
    # record at exit. None = off (bench.py keeps its own default ON — the
    # bench IS the official perf record).
    perf_ledger: str | None = None
    # Embedded status/health HTTP server (obs/server.py): /healthz /metrics
    # /status /flightrec served live from a daemon thread. None = off;
    # 0 = auto-pick a free port (chosen port logged as an obs_server event
    # and written into run_summary); a bind failure degrades to a no-op
    # with one warning — never crashes a run. Under multi-host, every rank
    # serves its own endpoints (use 0 when ranks share a host).
    server_port: int | None = None
    server_host: str = "127.0.0.1"
    # Cross-rank fleet view (obs/fleet.py): {"kind": "fleet_status"} records
    # merging per-rank heartbeats (step-lag + straggler naming) at epoch
    # boundaries, plus an independent watch thread under multi-host that
    # emits on straggler transitions even while the training thread is
    # wedged. Needs heartbeats; silent on single-rank runs.
    fleet: bool = True
    # SLO engine (obs/slo.py): objectives evaluated at epoch/scoring
    # boundaries -> {"kind": "slo_violation"} records (flight-recorder
    # mirrored), slo_* gauges, and the /healthz verdict. All None = engine
    # off. Throughput floors apply to steady epochs only: absolute ex/s,
    # and/or a fraction of the trailing perf-ledger baseline (clean records
    # only, the perf-sentry discipline — needs obs.perf_ledger).
    slo_throughput_floor: float | None = None
    slo_throughput_frac: float | None = None
    # Heartbeat staleness budget (seconds): the /healthz degraded threshold
    # and the epoch-boundary slo_violation check. None = the server's
    # default budget (obs/server.DEFAULT_STALE_S) for /healthz, no SLO.
    slo_heartbeat_stale_s: float | None = None
    # Max tolerated fraction of NaN/inf entries in a scoring pass's output.
    slo_nonfinite_frac: float | None = None
    # Eval-accuracy floor checked at each eval boundary.
    slo_eval_accuracy_floor: float | None = None
    # Serving SLOs (serve/): evaluated at every serve_stats point while the
    # service runs. p95 request latency budget in milliseconds (queue wait +
    # dispatch, measured per request)...
    slo_serve_p95_ms: float | None = None
    # ...max tolerated pending-request depth across tenants at a stats
    # point (queue-depth floor)...
    slo_serve_queue_depth: int | None = None
    # ...and the admission floor: max tolerated rejected fraction of all
    # submitted requests (429s / accepted+rejected) over the run so far.
    slo_serve_reject_frac: float | None = None
    # Fleet-level serving SLOs (serve/fleet.py): evaluated at every
    # serve_fleet stats point while a replicated fleet runs. Router-side
    # p95 request latency budget in milliseconds (includes retry/hedge
    # walls — what a client actually sees)...
    slo_fleet_p95_ms: float | None = None
    # ...and the availability floor: minimum fraction of replicas healthy
    # (routable) at a fleet stats point, in (0, 1].
    slo_fleet_available_frac: float | None = None
    # Cross-attempt recovery budget (seconds): time from the supervisor's
    # fault classification to the FIRST post-resume training step of the
    # relaunched attempt, computed from the lineage-stamped records in the
    # shared metrics stream (obs/lineage.py). Checked once per resumed
    # attempt; tools/postmortem.py applies the same budget offline via
    # --recovery-budget-s. None = no recovery SLO.
    slo_recovery_s: float | None = None


@dataclass
class TuningConfig:
    """Autotuner manifest consumption (tools/autotune.py writes the manifest;
    cli.py consults it at startup — see data_diet_distributed_tpu/tuning.py).

    ``manifest`` is the path to a sha256-digest-signed ``tuning_manifest.json``
    (null = the default ``artifacts/tuning_manifest.json`` if present).
    ``apply`` picks the stale-manifest policy: ``auto`` applies a matching
    manifest and skips a mismatched one with a logged reason, ``off`` never
    reads the manifest, ``strict`` turns every skip (missing file, geometry or
    backend mismatch) into a loud startup error. Explicit user config and
    already-set env gates always win over manifest knobs, in every mode."""

    manifest: str | None = None
    apply: str = "auto"


@dataclass
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    score: ScoreConfig = field(default_factory=ScoreConfig)
    prune: PruneConfig = field(default_factory=PruneConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    tuning: TuningConfig = field(default_factory=TuningConfig)

    def validate(self) -> "Config":
        if self.tuning.apply not in ("auto", "off", "strict"):
            raise ValueError(
                f"tuning.apply must be auto | off | strict, got "
                f"{self.tuning.apply!r}")
        if self.data.dataset not in ("cifar10", "cifar100", "synthetic",
                                     "synthetic_imagenet", "npz", "sharded"):
            raise ValueError(f"unknown dataset {self.data.dataset!r}")
        if self.data.data_plane not in ("auto", "streaming", "resident"):
            raise ValueError(
                f"data.data_plane must be auto | streaming | resident, got "
                f"{self.data.data_plane!r}")
        if self.data.prefetch_depth < 0:
            raise ValueError(
                f"data.prefetch_depth must be >= 0 (0 = synchronous), got "
                f"{self.data.prefetch_depth}")
        if self.data.host_cache_bytes <= 0:
            raise ValueError(
                f"data.host_cache_bytes must be > 0, got "
                f"{self.data.host_cache_bytes}")
        if self.data.read_retries < 0:
            raise ValueError(
                f"data.read_retries must be >= 0, got "
                f"{self.data.read_retries}")
        if self.data.read_backoff_s < 0:
            raise ValueError(
                f"data.read_backoff_s must be >= 0, got "
                f"{self.data.read_backoff_s}")
        if not 0.0 <= self.prune.sparsity < 1.0:
            raise ValueError(f"sparsity must be in [0, 1), got {self.prune.sparsity}")
        for s in self.prune.sweep:
            if not 0.0 < s < 1.0:
                raise ValueError(
                    f"prune.sweep entries must be in (0, 1), got {s}")
        if self.score.method not in ("el2n", "margin", "grand", "grand_vmap",
                                     "grand_last_layer", "forgetting", "aum"):
            raise ValueError(f"unknown score method {self.score.method!r}")
        if (self.score.method in ("forgetting", "aum")
                and self.score.pretrain_epochs < 1):
            raise ValueError(f"score.method={self.score.method} tracks the "
                             "training trajectory; set score.pretrain_epochs >= 1")
        if (self.score.method in ("forgetting", "aum")
                and self.score.score_ckpt_step is not None):
            raise ValueError(
                f"score.method={self.score.method} scores a training TRAJECTORY "
                "and cannot start from score.score_ckpt_step; unset one of them")
        if self.data.crop_pad < 0:
            raise ValueError(f"data.crop_pad must be >= 0, got {self.data.crop_pad}")
        if self.data.synthetic_noise <= 0:
            raise ValueError(
                f"data.synthetic_noise must be > 0, got {self.data.synthetic_noise}")
        if self.data.synthetic_clusters < 1:
            raise ValueError(
                f"data.synthetic_clusters must be >= 1, got "
                f"{self.data.synthetic_clusters}")
        if self.optim.warmup_epochs < 0:
            raise ValueError(
                f"optim.warmup_epochs must be >= 0, got {self.optim.warmup_epochs}")
        t_max = self.optim.cosine_t_max_epochs or self.train.num_epochs
        if self.optim.warmup_epochs and self.optim.warmup_epochs >= t_max:
            raise ValueError(
                f"optim.warmup_epochs ({self.optim.warmup_epochs}) must be "
                f"less than the cosine horizon ({t_max} epochs); raise "
                "optim.cosine_t_max_epochs or lower the warmup")
        if self.model.stem not in ("cifar", "imagenet"):
            raise ValueError(f"unknown stem {self.model.stem!r}")
        if self.prune.keep not in ("hardest", "easiest", "random"):
            raise ValueError(f"unknown keep policy {self.prune.keep!r}")
        if (self.data.num_classes is not None
                and self.model.num_classes != self.data.num_classes):
            # keep them in sync automatically rather than erroring
            self.model.num_classes = self.data.num_classes
        if self.data.batch_size <= 0 or self.train.num_epochs < 0:
            raise ValueError("batch_size must be positive, num_epochs non-negative")
        if self.train.chunk_steps is not None and self.train.chunk_steps < 0:
            raise ValueError(
                f"train.chunk_steps must be >= 0 (0/1 = per-step, null = "
                f"auto), got {self.train.chunk_steps}")
        if self.score.chunk_steps is not None and self.score.chunk_steps < 0:
            raise ValueError(
                f"score.chunk_steps must be >= 0 (0/1 = per-batch, null = "
                f"auto), got {self.score.chunk_steps}")
        r = self.resilience
        if r.step_timeout_s is not None and r.step_timeout_s <= 0:
            raise ValueError(
                f"resilience.step_timeout_s must be > 0 (or null to disable "
                f"the watchdog), got {r.step_timeout_s}")
        if r.nan_retry_budget < 0:
            raise ValueError(
                f"resilience.nan_retry_budget must be >= 0, got {r.nan_retry_budget}")
        if not 0.0 < r.nan_lr_factor <= 1.0:
            raise ValueError(
                f"resilience.nan_lr_factor must be in (0, 1], got {r.nan_lr_factor}")
        if r.probe_attempts < 1 or r.probe_timeout_s <= 0 or r.probe_backoff_s < 0:
            raise ValueError(
                "resilience probe settings need probe_attempts >= 1, "
                "probe_timeout_s > 0, probe_backoff_s >= 0; got "
                f"{r.probe_attempts}/{r.probe_timeout_s}/{r.probe_backoff_s}")
        if r.consensus_poll_steps < 1:
            raise ValueError(
                f"resilience.consensus_poll_steps must be >= 1, got "
                f"{r.consensus_poll_steps}")
        if r.consensus_grace_s <= 0:
            raise ValueError(
                f"resilience.consensus_grace_s must be > 0, got "
                f"{r.consensus_grace_s}")
        c = self.checkpoint
        if c.drain_timeout_s <= 0:
            raise ValueError(
                f"checkpoint.drain_timeout_s must be > 0, got "
                f"{c.drain_timeout_s}")
        if c.promote_delay_s < 0:
            raise ValueError(
                f"checkpoint.promote_delay_s must be >= 0, got "
                f"{c.promote_delay_s}")
        e = self.elastic
        if e.world is not None and e.world < 1:
            raise ValueError(f"elastic.world must be >= 1, got {e.world}")
        if e.min_world < 1:
            raise ValueError(
                f"elastic.min_world must be >= 1, got {e.min_world}")
        if e.max_world is not None and e.max_world < e.min_world:
            raise ValueError(
                f"elastic.max_world ({e.max_world}) must be >= "
                f"elastic.min_world ({e.min_world})")
        if e.world is not None and e.world < e.min_world:
            raise ValueError(
                f"elastic.world ({e.world}) must be >= elastic.min_world "
                f"({e.min_world}) — the supervisor never shrinks below the "
                "floor, so it cannot start there either")
        if e.world is not None and e.max_world is not None \
                and e.world > e.max_world:
            raise ValueError(
                f"elastic.world ({e.world}) must be <= elastic.max_world "
                f"({e.max_world})")
        if e.max_restarts < 0:
            raise ValueError(
                f"elastic.max_restarts must be >= 0, got {e.max_restarts}")
        if e.backoff_s < 0:
            raise ValueError(
                f"elastic.backoff_s must be >= 0, got {e.backoff_s}")
        if e.reap_timeout_s <= 0 or e.heartbeat_stale_s <= 0:
            raise ValueError(
                "elastic.reap_timeout_s and elastic.heartbeat_stale_s must "
                f"be > 0; got {e.reap_timeout_s}/{e.heartbeat_stale_s}")
        o = self.obs
        if o.snapshot_every_s < 0:
            raise ValueError(
                f"obs.snapshot_every_s must be >= 0 (0 disables periodic "
                f"snapshots), got {o.snapshot_every_s}")
        if o.heartbeat_interval_s < 0:
            raise ValueError(
                f"obs.heartbeat_interval_s must be >= 0, got "
                f"{o.heartbeat_interval_s}")
        if o.flightrec_capacity < 1:
            raise ValueError(
                f"obs.flightrec_capacity must be >= 1, got "
                f"{o.flightrec_capacity}")
        if o.profile_window_chunks < 1:
            raise ValueError(
                f"obs.profile_window_chunks must be >= 1, got "
                f"{o.profile_window_chunks}")
        if o.hbm_jump_frac <= 0:
            raise ValueError(
                f"obs.hbm_jump_frac must be > 0, got {o.hbm_jump_frac}")
        if o.score_hist_bins < 1:
            raise ValueError(
                f"obs.score_hist_bins must be >= 1, got {o.score_hist_bins}")
        if o.server_port is not None and not 0 <= o.server_port <= 65535:
            raise ValueError(
                f"obs.server_port must be in [0, 65535] (0 = auto-pick, "
                f"null = off), got {o.server_port}")
        if o.slo_throughput_floor is not None and o.slo_throughput_floor <= 0:
            raise ValueError(
                f"obs.slo_throughput_floor must be > 0, got "
                f"{o.slo_throughput_floor}")
        if (o.slo_throughput_frac is not None
                and not 0.0 < o.slo_throughput_frac <= 1.0):
            raise ValueError(
                f"obs.slo_throughput_frac must be in (0, 1], got "
                f"{o.slo_throughput_frac}")
        if (o.slo_heartbeat_stale_s is not None
                and o.slo_heartbeat_stale_s <= 0):
            raise ValueError(
                f"obs.slo_heartbeat_stale_s must be > 0, got "
                f"{o.slo_heartbeat_stale_s}")
        if (o.slo_nonfinite_frac is not None
                and not 0.0 <= o.slo_nonfinite_frac < 1.0):
            raise ValueError(
                f"obs.slo_nonfinite_frac must be in [0, 1), got "
                f"{o.slo_nonfinite_frac}")
        if (o.slo_eval_accuracy_floor is not None
                and not 0.0 <= o.slo_eval_accuracy_floor <= 1.0):
            raise ValueError(
                f"obs.slo_eval_accuracy_floor must be in [0, 1], got "
                f"{o.slo_eval_accuracy_floor}")
        if o.slo_recovery_s is not None and o.slo_recovery_s <= 0:
            raise ValueError(
                f"obs.slo_recovery_s must be > 0, got {o.slo_recovery_s}")
        if o.slo_serve_p95_ms is not None and o.slo_serve_p95_ms <= 0:
            raise ValueError(
                f"obs.slo_serve_p95_ms must be > 0, got {o.slo_serve_p95_ms}")
        if o.slo_serve_queue_depth is not None and o.slo_serve_queue_depth < 1:
            raise ValueError(
                f"obs.slo_serve_queue_depth must be >= 1, got "
                f"{o.slo_serve_queue_depth}")
        if (o.slo_serve_reject_frac is not None
                and not 0.0 <= o.slo_serve_reject_frac < 1.0):
            raise ValueError(
                f"obs.slo_serve_reject_frac must be in [0, 1), got "
                f"{o.slo_serve_reject_frac}")
        if o.slo_fleet_p95_ms is not None and o.slo_fleet_p95_ms <= 0:
            raise ValueError(
                f"obs.slo_fleet_p95_ms must be > 0, got {o.slo_fleet_p95_ms}")
        if (o.slo_fleet_available_frac is not None
                and not 0.0 < o.slo_fleet_available_frac <= 1.0):
            raise ValueError(
                f"obs.slo_fleet_available_frac must be in (0, 1], got "
                f"{o.slo_fleet_available_frac}")
        sv = self.serve
        if not 0 <= sv.port <= 65535:
            raise ValueError(
                f"serve.port must be in [0, 65535] (0 = auto-pick), got "
                f"{sv.port}")
        for m in sv.methods:
            if m not in SERVABLE_METHODS:
                raise ValueError(
                    f"serve.methods entries must be checkpoint-based score "
                    f"methods (trajectory methods cannot serve a warm "
                    f"checkpoint), got {m!r}")
        if sv.batch_size is not None and sv.batch_size < 1:
            raise ValueError(
                f"serve.batch_size must be >= 1 (or null for "
                f"score.batch_size), got {sv.batch_size}")
        if sv.max_queue < 1:
            raise ValueError(f"serve.max_queue must be >= 1, got "
                             f"{sv.max_queue}")
        if sv.coalesce_ms < 0:
            raise ValueError(f"serve.coalesce_ms must be >= 0, got "
                             f"{sv.coalesce_ms}")
        if (sv.retry_after_s <= 0 or sv.request_timeout_s <= 0
                or sv.drain_timeout_s <= 0 or sv.stats_every_s <= 0):
            raise ValueError(
                "serve timings need retry_after_s/request_timeout_s/"
                "drain_timeout_s/stats_every_s > 0; got "
                f"{sv.retry_after_s}/{sv.request_timeout_s}/"
                f"{sv.drain_timeout_s}/{sv.stats_every_s}")
        if sv.replicas < 1:
            raise ValueError(f"serve.replicas must be >= 1, got "
                             f"{sv.replicas}")
        if not 0 <= sv.router_port <= 65535:
            raise ValueError(
                f"serve.router_port must be in [0, 65535] (0 = auto-pick), "
                f"got {sv.router_port}")
        if sv.dispatch_stall_s is not None and sv.dispatch_stall_s <= 0:
            raise ValueError(
                f"serve.dispatch_stall_s must be > 0 (or null for no "
                f"watchdog), got {sv.dispatch_stall_s}")
        if sv.refresh_poll_s is not None and sv.refresh_poll_s <= 0:
            raise ValueError(
                f"serve.refresh_poll_s must be > 0 (or null for manual "
                f"refresh only), got {sv.refresh_poll_s}")
        if sv.route_retries < 0:
            raise ValueError(f"serve.route_retries must be >= 0, got "
                             f"{sv.route_retries}")
        if sv.breaker_failures < 1:
            raise ValueError(f"serve.breaker_failures must be >= 1, got "
                             f"{sv.breaker_failures}")
        if sv.breaker_reset_s <= 0:
            raise ValueError(f"serve.breaker_reset_s must be > 0, got "
                             f"{sv.breaker_reset_s}")
        if sv.hedge_ms is not None and sv.hedge_ms <= 0:
            raise ValueError(
                f"serve.hedge_ms must be > 0 (or null for no hedging), "
                f"got {sv.hedge_ms}")
        if sv.health_poll_s <= 0:
            raise ValueError(f"serve.health_poll_s must be > 0, got "
                             f"{sv.health_poll_s}")
        if sv.idempotency_cache < 1:
            raise ValueError(f"serve.idempotency_cache must be >= 1, got "
                             f"{sv.idempotency_cache}")
        if sv.hosts and sv.remote_launch is None:
            raise ValueError(
                "serve.hosts names remote placements but serve.remote_launch "
                "is null — every listed host is spawned through the launch "
                "template")
        if sv.remote_launch is not None and "{host}" not in sv.remote_launch:
            raise ValueError(
                f"serve.remote_launch must contain a {{host}} placeholder, "
                f"got {sv.remote_launch!r}")
        if sv.min_replicas is not None and sv.max_replicas is None:
            raise ValueError(
                "serve.min_replicas without serve.max_replicas — the "
                "autoscaler is enabled by setting max_replicas")
        if sv.max_replicas is not None:
            min_eff = (sv.min_replicas if sv.min_replicas is not None
                       else sv.replicas)
            if not 1 <= min_eff <= sv.replicas <= sv.max_replicas:
                raise ValueError(
                    f"autoscaler bounds need 1 <= min_replicas "
                    f"({min_eff}) <= replicas ({sv.replicas}) <= "
                    f"max_replicas ({sv.max_replicas})")
        if sv.scale_up_after < 1 or sv.scale_down_after < 1:
            raise ValueError(
                f"serve.scale_up_after/scale_down_after must be >= 1 "
                f"(hysteresis windows in stats ticks), got "
                f"{sv.scale_up_after}/{sv.scale_down_after}")
        if sv.scale_cooldown_s < 0:
            raise ValueError(f"serve.scale_cooldown_s must be >= 0, got "
                             f"{sv.scale_cooldown_s}")
        if sv.partition_after_misses < 1:
            raise ValueError(f"serve.partition_after_misses must be >= 1, "
                             f"got {sv.partition_after_misses}")
        if not 0 < sv.probe_backoff_s <= sv.probe_backoff_max_s:
            raise ValueError(
                f"probation backoff needs 0 < probe_backoff_s <= "
                f"probe_backoff_max_s, got {sv.probe_backoff_s}/"
                f"{sv.probe_backoff_max_s}")
        if sv.canary_requests is not None and sv.canary_requests < 1:
            raise ValueError(
                f"serve.canary_requests must be >= 1 (or null for no "
                f"canary hold), got {sv.canary_requests}")
        if sv.canary_timeout_s <= 0:
            raise ValueError(f"serve.canary_timeout_s must be > 0, got "
                             f"{sv.canary_timeout_s}")
        if not 0.0 <= sv.trace_sample_frac <= 1.0:
            raise ValueError(f"serve.trace_sample_frac must be in [0, 1], "
                             f"got {sv.trace_sample_frac}")
        if sv.trace_slow_ms is not None and sv.trace_slow_ms <= 0:
            raise ValueError(
                f"serve.trace_slow_ms must be > 0 (or null to follow "
                f"obs.slo_serve_p95_ms), got {sv.trace_slow_ms}")
        return self


def _from_dict(cls, d: dict[str, Any]):
    kwargs = {}
    valid = {f.name: f for f in fields(cls)}
    for key, value in d.items():
        if key not in valid:
            raise KeyError(f"unknown config key {key!r} for {cls.__name__}")
        f = valid[key]
        if isinstance(value, dict):
            # nested section: field type is a string under future annotations
            kwargs[key] = _from_dict(_resolve_type(f), value)
        elif isinstance(value, list) and isinstance(f.default, tuple):
            kwargs[key] = tuple(value)
        else:
            kwargs[key] = value
    return cls(**kwargs)


_TYPE_MAP = {
    "DataConfig": DataConfig, "ModelConfig": ModelConfig, "OptimConfig": OptimConfig,
    "ScoreConfig": ScoreConfig, "PruneConfig": PruneConfig, "TrainConfig": TrainConfig,
    "MeshConfig": MeshConfig, "OverlapConfig": OverlapConfig,
    "ParallelConfig": ParallelConfig, "CheckpointConfig": CheckpointConfig,
    "ObsConfig": ObsConfig, "ResilienceConfig": ResilienceConfig,
    "ElasticConfig": ElasticConfig, "ServeConfig": ServeConfig,
    "TuningConfig": TuningConfig,
}


def _resolve_type(f):
    name = f.type if isinstance(f.type, str) else f.type.__name__
    return _TYPE_MAP[name]


def load_config(path: str | None = None, overrides: list[str] | None = None) -> Config:
    """Build a Config from an optional YAML file plus ``dotted.key=value`` overrides.

    Override values are YAML-parsed, so ``optim.lr=0.1``, ``train.resume=true`` and
    ``score.seeds=[0,1,2]`` all coerce to the right types.
    """
    cfg = Config()
    if path is not None:
        with open(path) as fh:
            raw = yaml.safe_load(fh) or {}
        cfg = _from_dict(Config, raw)
    for item in overrides or []:
        if "=" not in item:
            raise ValueError(f"override {item!r} is not of the form key=value")
        dotted, _, raw_value = item.partition("=")
        value = yaml.safe_load(raw_value)
        node: Any = cfg
        *parents, leaf = dotted.split(".")
        for part in parents:
            node = getattr(node, part)
        if not hasattr(node, leaf):
            raise KeyError(f"unknown config key {dotted!r}")
        if isinstance(value, list) and isinstance(getattr(node, leaf), tuple):
            value = tuple(value)
        setattr(node, leaf, value)
    return cfg.validate()


def to_dict(cfg: Config) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def save_config(cfg: Config, path: str) -> None:
    with open(path, "w") as fh:
        yaml.safe_dump(to_dict(cfg), fh, sort_keys=False)
