"""Orbax checkpointing: ONE schema, true resume — plus the async LOCAL tier.

The reference has two incompatible ad-hoc ``torch.save`` schemas (``{'net','acc','epoch'}``
at ``trainer/trainer.py:64-71`` vs ``{'model_state_dict',...}`` at ``ddp.py:116-123``),
saves every epoch unconditionally, and cannot actually resume (optimizer/scheduler state
never restored — SURVEY §5.4). Here every checkpoint is the full
``{params, batch_stats, opt_state, step}`` pytree managed by Orbax: async-friendly,
multi-host safe (Orbax coordinates processes internally), retention-limited, and the
scoring phase can load any step's params — the ``score_ckpt_step`` knob replacing the
reference's hard-coded ``ckpt_19.pth`` (``train.py:61``).

MULTI-TIER (``checkpoint.local_tier``, ``LocalTier``): at pod scale the
durable filesystem is the step-stall — even async Orbax pays a
previous-save barrier plus a coordinated commit on shared storage. The
local tier makes the SAVE a rank-local fast path: each rank writes only the
leaf shards it OWNS (``replica_id == 0`` — params once across the fleet
under the sharded update, slots per-rank) to LOCAL disk with a per-rank
digest manifest, and a background thread PROMOTES completed saves to the
durable tier (``<dir>_tiered/``), re-verifying digests after the copy. A
step only counts as restorable once every rank's shards are promoted and
verified — so consensus restore (``verified_steps``) can never agree on a
half-promoted step — and the preemption path drains in-flight promotions
before the agreed exit 75 (``all_steps`` is the durability barrier, as
before). Readers need no config: tier steps are discovered from the path
convention, so any later run restores them like Orbax steps.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import TYPE_CHECKING, Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from .obs import registry as obs_registry
from .obs import tracing
from .resilience.integrity import (CheckpointCorrupt, build_manifest,
                                   verify_restored)
from .utils.io import atomic_write_json

if TYPE_CHECKING:  # avoid a circular import (train.loop uses this module)
    from .train.state import TrainState


def tiered_dir(directory: str) -> str:
    """The durable-tier path convention (sibling of the Orbax dir, like
    ``_stages.json``/``_sidechannel``): readers discover promoted tier steps
    here with no config."""
    return f"{os.path.abspath(directory)}_tiered"


def local_tier_dir(directory: str, configured: str | None = None) -> str:
    """The fast local-tier scratch root (point ``checkpoint.local_dir`` at
    genuinely local disk on real pods).

    A configured root is NAMESPACED by the checkpoint directory's identity
    (basename + path hash): operators point every job on a host at the same
    local SSD, and without the namespace two concurrent runs would collide
    on ``rank<k>/step_<n>`` — run A's promoter could then copy run B's
    freshly-replaced shards into A's durable tier with PASSING digests (the
    manifest and npz would both be B's)."""
    directory = os.path.abspath(directory)
    if configured is None:
        return f"{directory}_local"
    slug = (f"{os.path.basename(directory)}-"
            f"{hashlib.sha256(directory.encode()).hexdigest()[:10]}")
    return os.path.join(os.path.abspath(configured), slug)


def _sha(data: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()[:16]


def _step_dir(root: str, step: int) -> str:
    return os.path.join(root, f"step_{int(step)}")


def _payload_of(state: "TrainState") -> dict[str, Any]:
    return {"params": state.params, "batch_stats": state.batch_stats,
            "opt_state": state.opt_state, "step": state.step}


def _owned_shards(leaf) -> list[tuple[tuple, np.ndarray]]:
    """The ``(global_index, host_data)`` pieces of ``leaf`` THIS process
    owns. Ownership is ``replica_id == 0``: for sharded leaves every local
    shard owns its slice; for replicated leaves exactly one device in the
    fleet owns the whole — so the union over ranks covers every leaf exactly
    once, which is what makes the per-rank save a SHARDED save instead of a
    world-times-duplicated one. Non-jax leaves (host scalars) are owned by
    rank 0."""
    if not hasattr(leaf, "addressable_shards"):
        if jax.process_index() == 0:
            return [((), np.asarray(leaf))]
        return []
    out = []
    for sh in leaf.addressable_shards:
        if sh.replica_id != 0:
            continue
        out.append((sh.index, np.asarray(sh.data)))
    return out


def _index_json(index: tuple, shape: tuple) -> list[list[int]] | None:
    """A shard's global index as JSON (``[[start, stop], ...]`` per dim);
    None = the whole leaf."""
    if not index:
        return None
    out = []
    for sl, dim in zip(index, shape):
        out.append([int(sl.start or 0),
                    int(dim if sl.stop is None else sl.stop)])
    return out


def tier_steps(directory: str) -> list[int]:
    """Steps fully promoted to the durable tier: every rank named by the
    rank-0 marker has its own promotion marker present. Sorted ascending."""
    root = tiered_dir(directory)
    out = []
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return out
    for name in names:
        if not name.startswith("step_"):
            continue
        try:
            step = int(name[len("step_"):])
        except ValueError:
            continue
        sdir = os.path.join(root, name)
        try:
            with open(os.path.join(sdir, "promoted.rank0.json")) as fh:
                world = int(json.load(fh).get("world", 1))
        except (OSError, ValueError):
            continue
        if all(os.path.exists(os.path.join(sdir, f"promoted.rank{r}.json"))
               for r in range(world)):
            out.append(step)
    return sorted(out)


class LocalTier:
    """Per-rank local-disk saves + background promotion to the durable tier.

    ``save_local`` is the fast path the step loop pays: owned-shard
    device→host fetch, one npz + digest manifest to local disk, enqueue.
    The promoter thread copies each completed save to
    ``tiered_dir(directory)``, re-loads the copy to verify every digest,
    then writes this rank's atomic ``promoted.rank<k>.json`` marker — the
    durable commit point. ``drain`` (the preemption path, via
    ``CheckpointManager.all_steps``) bounds the wait on in-flight
    promotions. Promotion errors are logged (``{"kind": "ckpt_tier",
    "tier": "error"}``) and surfaced on drain — never raised from the
    background thread into nowhere."""

    def __init__(self, directory: str, *, local_dir: str | None = None,
                 promote: bool = True, drain_timeout_s: float = 120.0,
                 promote_delay_s: float = 0.0, max_to_keep: int = 20,
                 logger=None):
        self.rank = jax.process_index()
        self.world = jax.process_count()
        self.durable_root = tiered_dir(directory)
        self.local_root = os.path.join(
            local_tier_dir(directory, local_dir), f"rank{self.rank}")
        self.promote = promote
        self.drain_timeout_s = float(drain_timeout_s)
        self.promote_delay_s = float(promote_delay_s)
        self.max_to_keep = int(max_to_keep)
        self.logger = logger
        self.errors: list[str] = []
        #: The last drain's outcome ({"ok", "wait_s", "budget_s",
        #: "timed_out", "errors"}) — surfaced through
        #: ``CheckpointManager.drain_info`` so the preemption path's
        #: ``checkpoint_not_durable`` fault can report how much of the
        #: timeout budget the wait consumed (slow disk vs dead promotion).
        self.last_drain: dict[str, Any] | None = None
        self._pending: list[int] = []
        self._cond = threading.Condition()
        self._stop = False
        self._thread: threading.Thread | None = None
        os.makedirs(self.local_root, exist_ok=True)
        os.makedirs(self.durable_root, exist_ok=True)

    # ------------------------------------------------------------ fast path

    def save_local(self, step: int, state: "TrainState",
                   metrics: dict[str, Any] | None = None) -> None:
        """The step loop's save: owned shards → local disk, then enqueue the
        promotion. Rank 0's manifest additionally carries the STATE-level
        integrity manifest (``resilience/integrity.build_manifest`` — the
        same table the Orbax composite rides) and the epoch-metadata
        ``metrics`` dict resume reads."""
        t0 = time.perf_counter()
        payload = _payload_of(state)
        sdir = _step_dir(self.local_root, step)
        os.makedirs(sdir, exist_ok=True)
        arrays: dict[str, np.ndarray] = {}
        entries = []
        leaves_meta: dict[str, dict] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(payload)[0]:
            keystr = jax.tree_util.keystr(path)
            leaves_meta[keystr] = {
                "shape": [int(d) for d in getattr(leaf, "shape", ())],
                "dtype": str(getattr(leaf, "dtype", "int64")),
            }
            for i, (index, data) in enumerate(_owned_shards(leaf)):
                key = f"a{len(arrays)}"
                arrays[key] = data
                entries.append({
                    "key": key, "leaf": keystr,
                    "index": _index_json(index, getattr(leaf, "shape", ())),
                    "sha": _sha(data),
                })
        manifest: dict[str, Any] = {
            "version": 1, "step": int(step), "rank": self.rank,
            "world": self.world, "arrays": entries, "leaves": leaves_meta,
        }
        # EVERY rank computes the state manifest: its finiteness check is a
        # device reduction, which over data-axis-SHARDED params (the sharded
        # weight update) is a cross-process program every rank must launch —
        # a rank-0-only dispatch would deadlock the pod on the first tier
        # save. Only rank 0 persists the result (one copy is the contract).
        state_manifest = build_manifest(payload, step)
        if self.rank == 0:
            manifest["state_manifest"] = state_manifest
            if metrics:
                manifest["metrics"] = metrics
        # Atomic npz (temp + rename, same discipline as utils.io) — a kill
        # mid-save must never leave a truncated shard file a promotion
        # could trust.
        tmp = os.path.join(sdir, "shards.tmp.npz")
        np.savez(tmp, **arrays)
        os.replace(tmp, os.path.join(sdir, "shards.npz"))
        atomic_write_json(os.path.join(sdir, "manifest.json"), manifest)
        self._log(step, "local", wall_s=round(time.perf_counter() - t0, 4),
                  n_arrays=len(arrays))
        if self.promote:
            with self._cond:
                self._pending.append(int(step))
                self._cond.notify_all()
            self._ensure_thread()

    # ------------------------------------------------------------ promotion

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._worker, daemon=True,
                                            name="ckpt-tier-promoter")
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stop:
                    self._cond.wait(0.2)
                if self._stop and not self._pending:
                    return
                step = self._pending[0]
            try:
                if self.promote_delay_s:
                    time.sleep(self.promote_delay_s)
                self._promote(step)
            except Exception as exc:   # noqa: BLE001 — surfaced, never lost
                self.errors.append(f"step {step}: {exc!r}"[:300])
                self._log(step, "error", error=repr(exc)[:300])
            finally:
                with self._cond:
                    self._pending.remove(step)
                    self._cond.notify_all()

    def _promote(self, step: int) -> None:
        t0 = time.perf_counter()
        src = _step_dir(self.local_root, step)
        dst = _step_dir(self.durable_root, step)
        os.makedirs(dst, exist_ok=True)
        with open(os.path.join(src, "manifest.json")) as fh:
            manifest = json.load(fh)
        for name, out in (("shards.npz", f"rank{self.rank}.npz"),
                          ("manifest.json", f"rank{self.rank}.manifest.json")):
            tmp = os.path.join(dst, f".{out}.tmp")
            shutil.copyfile(os.path.join(src, name), tmp)
            os.replace(tmp, os.path.join(dst, out))
        # Verify the DURABLE copy against the save-time digests before the
        # marker makes it count: a torn/bit-flipped copy must stay invisible
        # to restore and consensus.
        with np.load(os.path.join(dst, f"rank{self.rank}.npz"),
                     allow_pickle=False) as d:
            for entry in manifest["arrays"]:
                got = _sha(d[entry["key"]])
                if got != entry["sha"]:
                    raise CheckpointCorrupt(
                        f"tier promotion of step {step}: array "
                        f"{entry['leaf']} digest {got} != saved "
                        f"{entry['sha']}")
        atomic_write_json(
            os.path.join(dst, f"promoted.rank{self.rank}.json"),
            {"step": int(step), "rank": self.rank, "world": self.world,
             "ts": round(time.time(), 3)})
        # The local copy is scratch; promoted = safe to reclaim.
        shutil.rmtree(src, ignore_errors=True)
        self._log(step, "durable",
                  wall_s=round(time.perf_counter() - t0, 4))
        self._retain()

    def _retain(self) -> None:
        """Bounded durable-tier retention: each rank prunes ITS files (and
        marker) for steps beyond ``max_to_keep``; the directory disappears
        when the last rank's prune empties it."""
        steps = []
        try:
            for name in os.listdir(self.durable_root):
                if name.startswith("step_"):
                    try:
                        steps.append(int(name[len("step_"):]))
                    except ValueError:
                        pass
        except FileNotFoundError:
            return
        for step in sorted(steps)[:-self.max_to_keep] if len(
                steps) > self.max_to_keep else []:
            sdir = _step_dir(self.durable_root, step)
            for name in (f"promoted.rank{self.rank}.json",
                         f"rank{self.rank}.npz",
                         f"rank{self.rank}.manifest.json"):
                try:
                    os.remove(os.path.join(sdir, name))
                except FileNotFoundError:
                    pass
            try:
                os.rmdir(sdir)
            except OSError:
                pass   # other ranks' files remain — theirs to prune

    # ------------------------------------------------------------- control

    def drain(self, timeout_s: float | None = None) -> bool:
        """Block until every enqueued promotion has finished (the durability
        barrier the preemption path rides). Returns False on timeout OR when
        any promotion has FAILED (``self.errors`` — each failure also logged
        as a ``ckpt_tier`` error record at fire time). Either way the real
        durability contract is the step LISTING: a step whose promotion
        failed never appears in ``tier_steps``/``all_steps``, so restore and
        consensus can never trust it."""
        budget = self.drain_timeout_s if timeout_s is None else timeout_s
        t0 = time.perf_counter()
        with self._cond:
            meaningful = bool(self._pending)
            ok = self._cond.wait_for(lambda: not self._pending, budget)
        if meaningful or (self.errors and self.last_drain is None):
            # Only a drain that actually WAITED (or the FIRST failed one)
            # is a triage record: the read paths call this on every
            # listing, and an instant no-op must not clobber the stats of
            # the wait that mattered — with errors standing, every later
            # drain is an instant no-op too.
            self.last_drain = {"ok": bool(ok and not self.errors),
                               "wait_s": round(time.perf_counter() - t0, 3),
                               "budget_s": float(budget),
                               "timed_out": not ok,
                               "errors": len(self.errors)}
        if not ok:
            self._log(-1, "error", wait_s=self.last_drain["wait_s"],
                      budget_s=float(budget),
                      error=f"drain timed out after {budget}s with "
                            f"{len(self._pending)} promotion(s) in flight")
        return ok and not self.errors

    def close(self) -> None:
        self.drain()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _log(self, step: int, tier: str, **fields) -> None:
        obs_registry.inc(f"ckpt_tier_{tier}")
        if self.logger is not None:
            self.logger.log("ckpt_tier", step=int(step), tier=tier,
                            rank=self.rank, **fields)


def _read_tier_manifests(directory: str, step: int) -> list[dict]:
    sdir = _step_dir(tiered_dir(directory), step)
    out = []
    with open(os.path.join(sdir, "promoted.rank0.json")) as fh:
        world = int(json.load(fh).get("world", 1))
    for r in range(world):
        with open(os.path.join(sdir, f"rank{r}.manifest.json")) as fh:
            out.append(json.load(fh))
    return out


def restore_tier_payload(directory: str, step: int) -> dict[str, Any]:
    """Assemble the full host payload ``{leaf_keystr: np.ndarray}`` for a
    promoted tier step from every rank's shard files, digest-verifying each
    array as it is read."""
    sdir = _step_dir(tiered_dir(directory), step)
    manifests = _read_tier_manifests(directory, step)
    leaves: dict[str, np.ndarray] = {}
    meta = manifests[0]["leaves"]
    for m in manifests:
        meta.update(m["leaves"])
    for key, info in meta.items():
        leaves[key] = np.zeros(tuple(info["shape"]), np.dtype(info["dtype"]))
    for m in manifests:
        with np.load(os.path.join(sdir, f"rank{m['rank']}.npz"),
                     allow_pickle=False) as d:
            for entry in m["arrays"]:
                data = d[entry["key"]]
                if _sha(data) != entry["sha"]:
                    raise CheckpointCorrupt(
                        f"tier step {step}: array {entry['leaf']} (rank "
                        f"{m['rank']}) failed digest verification")
                if entry["index"] is None:
                    leaves[entry["leaf"]] = data.reshape(
                        leaves[entry["leaf"]].shape)
                else:
                    sl = tuple(slice(s, e) for s, e in entry["index"])
                    leaves[entry["leaf"]][sl] = data
    return leaves


def tier_map(directory: str, local_dir: str | None = None) -> dict[str, str]:
    """``{step: tier}`` for every checkpoint under ``directory`` — the
    provenance block the stage manifest records (``"durable"`` = promoted
    tier step, ``"local"`` = saved but never promoted (rank-0 view),
    ``"orbax"`` = classic composite). ``local_dir``: the configured
    ``checkpoint.local_dir`` when one is set — the "local" scan must look
    where the saves actually went."""
    out: dict[str, str] = {}
    try:
        mngr = ocp.CheckpointManager(os.path.abspath(directory))
        for s in mngr.all_steps():
            out[str(int(s))] = "orbax"
        mngr.close()
    except Exception:   # noqa: BLE001 — absent/foreign dir: no orbax steps
        pass
    for s in tier_steps(directory):
        out[str(int(s))] = "durable"
    local_root = os.path.join(local_tier_dir(directory, local_dir), "rank0")
    try:
        for name in os.listdir(local_root):
            if name.startswith("step_"):
                out.setdefault(name[len("step_"):], "local")
    except FileNotFoundError:
        pass
    return out


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 20,
                 tier=None, logger=None):
        """``tier`` (a ``config.CheckpointConfig`` with ``local_tier=True``,
        or None) arms the multi-tier WRITE path: saves go through
        ``LocalTier`` (fast per-rank local save + background promotion)
        instead of the Orbax composite. READERS never need it — promoted
        tier steps are discovered from the path convention and served by
        ``all_steps``/``restore``/``manifest``/``metrics`` transparently,
        next to any Orbax steps in the same directory."""
        directory = os.path.abspath(directory)
        self.directory = directory
        if jax.process_index() == 0:
            os.makedirs(directory, exist_ok=True)
        self._tier: LocalTier | None = None
        if tier is not None and getattr(tier, "local_tier", False):
            self._tier = LocalTier(
                directory, local_dir=tier.local_dir, promote=tier.promote,
                drain_timeout_s=tier.drain_timeout_s,
                promote_delay_s=tier.promote_delay_s,
                max_to_keep=max_to_keep, logger=logger)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True),
        )

    def save(self, step: int, state: "TrainState",
             metrics: dict[str, Any] | None = None) -> None:
        if self._tier is not None:
            # The multi-tier fast path: rank-local shard write + background
            # promotion — the step loop never waits on durable storage. The
            # span measures the LOCAL write, which is the stall actually
            # paid (the promotion wall rides the ckpt_tier records).
            with tracing.span("checkpoint_save", cat="checkpoint",
                              step=step, tier="local"), \
                    obs_registry.timed("checkpoint_save_s"):
                self._tier.save_local(step, state, metrics)
            return
        payload = {"params": state.params, "batch_stats": state.batch_stats,
                   "opt_state": state.opt_state, "step": state.step}
        composite = {"state": ocp.args.StandardSave(payload),
                     # Integrity manifest (leaf paths/shapes/dtypes, step,
                     # params finite-ness) rides in the same composite — atomic
                     # with the state it describes; restore_verified checks it.
                     "manifest": ocp.args.JsonSave(build_manifest(payload, step))}
        if metrics:
            # Item name "meta", NOT "metrics": CheckpointManager reserves
            # "metrics" for its own best-checkpoint tracking
            # (orbax RESERVED_ITEM_NAMES) — using it makes every save raise.
            composite["meta"] = ocp.args.JsonSave(metrics)
        # Saves are ASYNC: serialization overlaps the next epoch's compute
        # (Orbax snapshots the arrays before returning, so donation/mutation of
        # ``state`` afterwards is safe). Any still-running previous save is
        # waited on here (not at the end of this one) — the stall shrinks from
        # full-serialization-per-save to only what the intervening epoch didn't
        # already cover. Readers (latest_step/all_steps/restore/close) barrier.
        # The span/histogram therefore measures the DISPATCH cost the training
        # loop actually pays (previous-save barrier + array snapshot), which is
        # exactly the stall a perf investigation needs to see.
        with tracing.span("checkpoint_save", cat="checkpoint", step=step), \
                obs_registry.timed("checkpoint_save_s"):
            self._mngr.wait_until_finished()
            if step in self._mngr.all_steps():
                # A stale checkpoint from an earlier run sharing this directory
                # (same step numbering) — overwrite it; Orbax otherwise raises
                # StepAlreadyExistsError and the stale payload would shadow
                # this run.
                self._mngr.delete(step)
            # force=True: Orbax's default policy silently skips saves at steps
            # <= the directory's latest step, so a stale HIGHER-numbered
            # checkpoint would otherwise swallow every save this run makes.
            self._mngr.save(step, args=ocp.args.Composite(**composite),
                            force=True)

    def _tier_steps(self) -> list[int]:
        return tier_steps(self.directory)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self) -> list[int]:
        # Durability barrier, both tiers: in-flight async Orbax saves land,
        # in-flight tier promotions drain — the preemption path calls this
        # before claiming a durable step.
        if self._tier is not None:
            self._tier.drain()
        self._mngr.wait_until_finished()
        return sorted(set(self._mngr.all_steps()) | set(self._tier_steps()))

    def restore(self, state: "TrainState", step: int | None = None) -> "TrainState":
        """Restore into (the abstract shape of) ``state`` — exact resume including
        optimizer state and step counter. Tier steps (promoted shard files)
        and Orbax composites are served transparently from the same call."""
        self._mngr.wait_until_finished()   # an in-flight async save may be it
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        if int(step) in self._tier_steps():
            return self._restore_tier(state, int(step))
        template = {"params": state.params, "batch_stats": state.batch_stats,
                    "opt_state": state.opt_state, "step": state.step}
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        with tracing.span("checkpoint_restore", cat="checkpoint", step=step), \
                obs_registry.timed("checkpoint_restore_s"):
            restored = self._mngr.restore(
                step,
                args=ocp.args.Composite(state=ocp.args.StandardRestore(abstract)))
        payload = restored["state"]
        return state.replace(params=payload["params"],
                             batch_stats=payload["batch_stats"],
                             opt_state=payload["opt_state"],
                             step=payload["step"])

    def _restore_tier(self, state: "TrainState", step: int) -> "TrainState":
        """Assemble a promoted tier step (digest-verified per array) and
        place it with the TEMPLATE's shardings — the tier twin of Orbax's
        StandardRestore(abstract)."""
        from .parallel.mesh import _device_put
        with tracing.span("checkpoint_restore", cat="checkpoint", step=step,
                          tier="durable"), \
                obs_registry.timed("checkpoint_restore_s"):
            leaves = restore_tier_payload(self.directory, step)
            template = _payload_of(state)
            flat, treedef = jax.tree_util.tree_flatten_with_path(template)
            out = []
            for path, leaf in flat:
                key = jax.tree_util.keystr(path)
                if key not in leaves:
                    raise CheckpointCorrupt(
                        f"tier step {step}: leaf {key} missing from the "
                        "promoted shard files — incompatible state tree")
                value = leaves[key]
                if hasattr(leaf, "sharding"):
                    out.append(_device_put(
                        np.asarray(value, dtype=leaf.dtype), leaf.sharding))
                elif isinstance(leaf, (int, np.integer)):
                    out.append(int(value))
                else:
                    out.append(np.asarray(value))
            payload = jax.tree_util.tree_unflatten(treedef, out)
        return state.replace(params=payload["params"],
                             batch_stats=payload["batch_stats"],
                             opt_state=payload["opt_state"],
                             step=payload["step"])

    def manifest(self, step: int) -> dict[str, Any] | None:
        """The integrity manifest saved alongside a step (None for checkpoints
        written before manifests existed — those stay restorable unverified)."""
        if int(step) in self._tier_steps():
            manifests = _read_tier_manifests(self.directory, int(step))
            return manifests[0].get("state_manifest")
        self._mngr.wait_until_finished()
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(manifest=ocp.args.JsonRestore()))
            return restored["manifest"]
        except KeyError:    # pre-manifest checkpoint — a legitimate None;
            return None     # real IO/corruption errors propagate

    def restore_verified(self, state: "TrainState", step: int | None = None,
                         on_fallback=None) -> tuple["TrainState", int]:
        """Restore the newest durable step that passes manifest verification.

        Candidates are every durable step (``<= step`` when one is pinned —
        the recovery path pins its own latest save, and falling back FORWARD
        to a newer stale checkpoint would resume someone else's run), newest
        first. A candidate that fails — Orbax deserialization of a truncated
        payload, or manifest drift (``resilience/integrity.py``) — is reported
        via ``on_fallback(step=, error=)`` and the next-oldest is tried;
        ``CheckpointCorrupt`` is raised only when every candidate fails.

        Returns ``(state, restored_step)`` so the caller reads epoch metadata
        for the step actually used, not the one it asked for.
        """
        candidates = [s for s in sorted(self.all_steps(), reverse=True)
                      if step is None or s <= step]
        if not candidates:
            raise FileNotFoundError("no checkpoint to restore")
        last_err: Exception | None = None
        for s in candidates:
            try:
                restored = self.restore(state, s)
                verify_restored(
                    {"params": restored.params,
                     "batch_stats": restored.batch_stats,
                     "opt_state": restored.opt_state, "step": restored.step},
                    self.manifest(s), step=s)
                return restored, s
            except Exception as err:  # noqa: BLE001 — any failed candidate falls back
                last_err = err
                if on_fallback is not None:
                    on_fallback(step=s, error=repr(err)[:300])
        raise CheckpointCorrupt(
            f"all {len(candidates)} durable checkpoint(s) "
            f"{candidates} failed restore/verification; last error: "
            f"{last_err!r}") from last_err

    def verified_steps(self, max_step: int | None = None) -> list[int]:
        """Durable steps whose save-time manifest loads and matches its step —
        the cheap (metadata-only, no tensor IO) candidate set each rank
        contributes to consensus restore (``Consensus.agree_restore_step``).
        Pre-manifest checkpoints count, matching ``restore_verified``'s
        restorable-unverified contract; payload-level truncation is caught
        later by ``restore_checked`` on the one agreed step."""
        out = []
        for s in self.all_steps():
            if max_step is not None and s > max_step:
                continue
            try:
                m = self.manifest(s)
            except Exception:  # noqa: BLE001 — unreadable manifest: not a candidate
                continue
            if m is None or int(m.get("step", s)) == int(s):
                out.append(s)
        return sorted(out)

    def restore_checked(self, state: "TrainState", step: int) -> "TrainState":
        """Restore EXACTLY ``step`` with manifest verification and NO
        fallback — the consensus restore path. Falling back per-rank to an
        earlier step (``restore_verified``) would silently desync the ranks
        the agreed step exists to keep in lockstep; a rank that cannot
        restore the agreed step must fail loudly instead."""
        restored = self.restore(state, step)
        verify_restored(
            {"params": restored.params, "batch_stats": restored.batch_stats,
             "opt_state": restored.opt_state, "step": restored.step},
            self.manifest(step), step=step)
        return restored

    def metrics(self, step: int | None = None) -> dict[str, Any] | None:
        """The metrics JSON saved alongside a step (None if absent) — carries
        the epoch counter, so resume does not have to derive it from
        ``steps_per_epoch`` (wrong whenever the resuming run uses a different
        batch size than the saving run)."""
        self._mngr.wait_until_finished()
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if int(step) in self._tier_steps():
            manifests = _read_tier_manifests(self.directory, int(step))
            return manifests[0].get("metrics")
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
            return restored["meta"]
        except KeyError:    # saved without a metrics item — a legitimate None;
            return None     # real IO/corruption errors propagate

    def await_step(self, step: int, timeout_s: float | None = None) -> list[int]:
        """Bounded wait for ``step`` to appear in the durable LISTING — the
        preemption path's cross-rank completion gap: each rank's drain
        covers only its OWN promotions, but a tier step counts only once
        EVERY rank's marker lands, so a rank that drained fast can list a
        just-promoted step as absent for the moment its slower peers are
        still copying. Filesystem polling only (no collective — peers may
        be mid-teardown), bounded by the tier drain budget; returns the
        final listing either way. Orbax-only managers return the listing
        immediately (the Orbax save is itself collective — landing is
        all-rank by construction)."""
        steps = self.all_steps()
        if self._tier is None or step in steps or self._tier.world <= 1:
            return steps
        # Waiting is only meaningful for PEERS' markers: if this rank's own
        # marker is not down (its promotion failed or timed out), no peer
        # can complete the step — report the honest miss immediately.
        own = os.path.join(tiered_dir(self.directory), f"step_{int(step)}",
                           f"promoted.rank{self._tier.rank}.json")
        if not os.path.exists(own):
            return steps
        budget = (self._tier.drain_timeout_s if timeout_s is None
                  else timeout_s)
        deadline = time.monotonic() + budget
        while step not in steps and time.monotonic() < deadline:
            time.sleep(0.1)
            steps = self.all_steps()
        return steps

    def drain_info(self) -> dict[str, Any] | None:
        """The last tier drain's outcome (None without a tier or before any
        drain) — how long the durability barrier actually waited against its
        budget, so a lost durable-step claim can be triaged as slow-disk
        (budget consumed, timed out) vs dead-promotion (failed fast)."""
        if self._tier is None:
            return None
        return self._tier.last_drain

    def saved_world(self, step: int) -> int | None:
        """The process count the checkpoint at ``step`` was SAVED by (tier
        steps record it in every rank manifest; Orbax composites don't —
        None). The elastic resume path logs it so a recovery onto a
        different world size is pinned in the stream, not inferred."""
        try:
            if int(step) in self._tier_steps():
                return int(_read_tier_manifests(self.directory,
                                                int(step))[0]["world"])
        except (OSError, TypeError, ValueError, KeyError):
            return None
        return None

    def restore_variables(self, state: "TrainState", step: int | None = None):
        """Params + batch_stats only — what the scoring phase needs (reference loads
        checkpoint key ``"net"`` for scoring, ``train.py:63``)."""
        restored = self.restore(state, step)
        return {"params": restored.params, "batch_stats": restored.batch_stats}

    def close(self) -> None:
        if self._tier is not None:
            self._tier.close()
        self._mngr.wait_until_finished()
        self._mngr.close()
