"""Orbax checkpointing: ONE schema, true resume.

The reference has two incompatible ad-hoc ``torch.save`` schemas (``{'net','acc','epoch'}``
at ``trainer/trainer.py:64-71`` vs ``{'model_state_dict',...}`` at ``ddp.py:116-123``),
saves every epoch unconditionally, and cannot actually resume (optimizer/scheduler state
never restored — SURVEY §5.4). Here every checkpoint is the full
``{params, batch_stats, opt_state, step}`` pytree managed by Orbax: async-friendly,
multi-host safe (Orbax coordinates processes internally), retention-limited, and the
scoring phase can load any step's params — the ``score_ckpt_step`` knob replacing the
reference's hard-coded ``ckpt_19.pth`` (``train.py:61``).
"""

from __future__ import annotations

import os
from typing import TYPE_CHECKING, Any

import jax
import orbax.checkpoint as ocp

from .obs import registry as obs_registry
from .obs import tracing
from .resilience.integrity import (CheckpointCorrupt, build_manifest,
                                   verify_restored)

if TYPE_CHECKING:  # avoid a circular import (train.loop uses this module)
    from .train.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 20):
        directory = os.path.abspath(directory)
        self.directory = directory
        if jax.process_index() == 0:
            os.makedirs(directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True),
        )

    def save(self, step: int, state: "TrainState",
             metrics: dict[str, Any] | None = None) -> None:
        payload = {"params": state.params, "batch_stats": state.batch_stats,
                   "opt_state": state.opt_state, "step": state.step}
        composite = {"state": ocp.args.StandardSave(payload),
                     # Integrity manifest (leaf paths/shapes/dtypes, step,
                     # params finite-ness) rides in the same composite — atomic
                     # with the state it describes; restore_verified checks it.
                     "manifest": ocp.args.JsonSave(build_manifest(payload, step))}
        if metrics:
            # Item name "meta", NOT "metrics": CheckpointManager reserves
            # "metrics" for its own best-checkpoint tracking
            # (orbax RESERVED_ITEM_NAMES) — using it makes every save raise.
            composite["meta"] = ocp.args.JsonSave(metrics)
        # Saves are ASYNC: serialization overlaps the next epoch's compute
        # (Orbax snapshots the arrays before returning, so donation/mutation of
        # ``state`` afterwards is safe). Any still-running previous save is
        # waited on here (not at the end of this one) — the stall shrinks from
        # full-serialization-per-save to only what the intervening epoch didn't
        # already cover. Readers (latest_step/all_steps/restore/close) barrier.
        # The span/histogram therefore measures the DISPATCH cost the training
        # loop actually pays (previous-save barrier + array snapshot), which is
        # exactly the stall a perf investigation needs to see.
        with tracing.span("checkpoint_save", cat="checkpoint", step=step), \
                obs_registry.timed("checkpoint_save_s"):
            self._mngr.wait_until_finished()
            if step in self._mngr.all_steps():
                # A stale checkpoint from an earlier run sharing this directory
                # (same step numbering) — overwrite it; Orbax otherwise raises
                # StepAlreadyExistsError and the stale payload would shadow
                # this run.
                self._mngr.delete(step)
            # force=True: Orbax's default policy silently skips saves at steps
            # <= the directory's latest step, so a stale HIGHER-numbered
            # checkpoint would otherwise swallow every save this run makes.
            self._mngr.save(step, args=ocp.args.Composite(**composite),
                            force=True)

    def latest_step(self) -> int | None:
        self._mngr.wait_until_finished()
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        self._mngr.wait_until_finished()
        return list(self._mngr.all_steps())

    def restore(self, state: "TrainState", step: int | None = None) -> "TrainState":
        """Restore into (the abstract shape of) ``state`` — exact resume including
        optimizer state and step counter."""
        self._mngr.wait_until_finished()   # an in-flight async save may be it
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        template = {"params": state.params, "batch_stats": state.batch_stats,
                    "opt_state": state.opt_state, "step": state.step}
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        with tracing.span("checkpoint_restore", cat="checkpoint", step=step), \
                obs_registry.timed("checkpoint_restore_s"):
            restored = self._mngr.restore(
                step,
                args=ocp.args.Composite(state=ocp.args.StandardRestore(abstract)))
        payload = restored["state"]
        return state.replace(params=payload["params"],
                             batch_stats=payload["batch_stats"],
                             opt_state=payload["opt_state"],
                             step=payload["step"])

    def manifest(self, step: int) -> dict[str, Any] | None:
        """The integrity manifest saved alongside a step (None for checkpoints
        written before manifests existed — those stay restorable unverified)."""
        self._mngr.wait_until_finished()
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(manifest=ocp.args.JsonRestore()))
            return restored["manifest"]
        except KeyError:    # pre-manifest checkpoint — a legitimate None;
            return None     # real IO/corruption errors propagate

    def restore_verified(self, state: "TrainState", step: int | None = None,
                         on_fallback=None) -> tuple["TrainState", int]:
        """Restore the newest durable step that passes manifest verification.

        Candidates are every durable step (``<= step`` when one is pinned —
        the recovery path pins its own latest save, and falling back FORWARD
        to a newer stale checkpoint would resume someone else's run), newest
        first. A candidate that fails — Orbax deserialization of a truncated
        payload, or manifest drift (``resilience/integrity.py``) — is reported
        via ``on_fallback(step=, error=)`` and the next-oldest is tried;
        ``CheckpointCorrupt`` is raised only when every candidate fails.

        Returns ``(state, restored_step)`` so the caller reads epoch metadata
        for the step actually used, not the one it asked for.
        """
        candidates = [s for s in sorted(self.all_steps(), reverse=True)
                      if step is None or s <= step]
        if not candidates:
            raise FileNotFoundError("no checkpoint to restore")
        last_err: Exception | None = None
        for s in candidates:
            try:
                restored = self.restore(state, s)
                verify_restored(
                    {"params": restored.params,
                     "batch_stats": restored.batch_stats,
                     "opt_state": restored.opt_state, "step": restored.step},
                    self.manifest(s), step=s)
                return restored, s
            except Exception as err:  # noqa: BLE001 — any failed candidate falls back
                last_err = err
                if on_fallback is not None:
                    on_fallback(step=s, error=repr(err)[:300])
        raise CheckpointCorrupt(
            f"all {len(candidates)} durable checkpoint(s) "
            f"{candidates} failed restore/verification; last error: "
            f"{last_err!r}") from last_err

    def verified_steps(self, max_step: int | None = None) -> list[int]:
        """Durable steps whose save-time manifest loads and matches its step —
        the cheap (metadata-only, no tensor IO) candidate set each rank
        contributes to consensus restore (``Consensus.agree_restore_step``).
        Pre-manifest checkpoints count, matching ``restore_verified``'s
        restorable-unverified contract; payload-level truncation is caught
        later by ``restore_checked`` on the one agreed step."""
        out = []
        for s in self.all_steps():
            if max_step is not None and s > max_step:
                continue
            try:
                m = self.manifest(s)
            except Exception:  # noqa: BLE001 — unreadable manifest: not a candidate
                continue
            if m is None or int(m.get("step", s)) == int(s):
                out.append(s)
        return sorted(out)

    def restore_checked(self, state: "TrainState", step: int) -> "TrainState":
        """Restore EXACTLY ``step`` with manifest verification and NO
        fallback — the consensus restore path. Falling back per-rank to an
        earlier step (``restore_verified``) would silently desync the ranks
        the agreed step exists to keep in lockstep; a rank that cannot
        restore the agreed step must fail loudly instead."""
        restored = self.restore(state, step)
        verify_restored(
            {"params": restored.params, "batch_stats": restored.batch_stats,
             "opt_state": restored.opt_state, "step": restored.step},
            self.manifest(step), step=step)
        return restored

    def metrics(self, step: int | None = None) -> dict[str, Any] | None:
        """The metrics JSON saved alongside a step (None if absent) — carries
        the epoch counter, so resume does not have to derive it from
        ``steps_per_epoch`` (wrong whenever the resuming run uses a different
        batch size than the saving run)."""
        self._mngr.wait_until_finished()
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        try:
            restored = self._mngr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))
            return restored["meta"]
        except KeyError:    # saved without a metrics item — a legitimate None;
            return None     # real IO/corruption errors propagate

    def restore_variables(self, state: "TrainState", step: int | None = None):
        """Params + batch_stats only — what the scoring phase needs (reference loads
        checkpoint key ``"net"`` for scoring, ``train.py:63``)."""
        restored = self.restore(state, step)
        return {"params": restored.params, "batch_stats": restored.batch_stats}

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
