# %% [markdown]
# # Data Diet scoring walkthrough
#
# Interactive counterpart to the reference's `test.ipynb` (its only "test"
# artifact — a manual replay of the scoring workflow: load a checkpointed model,
# run the EL2N loop, sort, keep the top half). Same journey here, but on the
# TPU-native stack: each step below is one notebook cell (`# %%` markers — open
# in VS Code / Jupytext, or just `python examples/walkthrough.py`).
#
# Runs on anything (CPU included) in ~a minute; no datasets or hardware needed.
# (From a source checkout, run as `PYTHONPATH=. python examples/walkthrough.py`,
# or `pip install -e .` first.)

# %% Setup: a mesh over every visible device, synthetic CIFAR-shaped data
from data_diet_distributed_tpu.config import load_config
from data_diet_distributed_tpu.data.pipeline import BatchSharder
from data_diet_distributed_tpu.models import create_model_from_cfg
from data_diet_distributed_tpu.parallel.mesh import make_mesh
from data_diet_distributed_tpu.train.loop import fit, load_data_for

# tiny_cnn keeps this runnable in ~a minute on one CPU core; on a TPU, swap in
# model.arch=resnet18 and data.synthetic_size=50000 — nothing else changes.
cfg = load_config(None, [
    "data.dataset=synthetic", "data.synthetic_size=2048", "data.batch_size=128",
    "model.arch=tiny_cnn", "train.num_epochs=1", "train.half_precision=false",
    "train.log_every_steps=1000",
])
mesh = make_mesh(cfg.mesh)
sharder = BatchSharder(mesh)
train_ds, test_ds = load_data_for(cfg)
print(f"mesh={dict(mesh.shape)}  train={len(train_ds)} examples")

# %% Train briefly — scores are computed from an EARLY checkpoint (the paper
# scores at epoch ~10-20 of 200; the reference hard-loads ckpt_19.pth).
result = fit(cfg, train_ds, test_ds, mesh=mesh, sharder=sharder)
print(f"pretrain: {result.history[-1]}")

# %% Score every example: EL2N = ||softmax(f(x)) - onehot(y)||2 per example,
# sharded over the mesh (the reference scored on ONE GPU, ddp.py:56).
from data_diet_distributed_tpu.ops.scoring import score_dataset

model = create_model_from_cfg(cfg)
variables = result.state.variables
el2n = score_dataset(model, [variables], train_ds, method="el2n",
                     batch_size=256, sharder=sharder)
print(f"EL2N: mean={el2n.mean():.3f} std={el2n.std():.3f}")

# %% GraNd = per-example gradient norm over ALL parameters — the score the
# reference lacks. The batched exact algorithm (ops/grand_batched.py) computes
# it without per-example backwards.
grand = score_dataset(model, [variables], train_ds, method="grand",
                      batch_size=256, sharder=sharder)
print(f"GraNd: mean={grand.mean():.3f} std={grand.std():.3f}")

# %% Compare the two rankings. (On real data with enough pretraining they
# correlate strongly — the paper's observation; on randomly-labeled synthetic
# data after one epoch, expect noise.)
from data_diet_distributed_tpu.utils.stats import spearman

print(f"spearman(EL2N, GraNd) = {spearman(el2n, grand):.3f}")

# %% Prune: keep the hardest half (the reference's sort + top-k,
# get_scores_and_prune.py:22-27, as one call).
from data_diet_distributed_tpu.pruning import select_indices

kept = select_indices(grand, train_ds.indices, sparsity=0.5, keep="hardest")
subset = train_ds.subset(kept)
print(f"kept {len(subset)}/{len(train_ds)} hardest examples")

# %% Retrain a FRESH model on the pruned subset and evaluate.
retrain = fit(cfg, subset, test_ds, mesh=mesh, sharder=sharder,
              seed=cfg.train.seed + 1, tag="retrain")
print(f"retrain on 50%: test_accuracy={retrain.final_test_accuracy:.3f}")

# %% Forgetting-events scores (Toneva et al. 2019) — the third scoring method:
# train-and-track instead of score-from-checkpoint. The tracker counts
# correct->incorrect transitions per example across epochs.
import copy

from data_diet_distributed_tpu.train.loop import forgetting_scores
from data_diet_distributed_tpu.obs import MetricsLogger

cfg_f = copy.deepcopy(cfg)
cfg_f.score.method = "forgetting"
cfg_f.score.pretrain_epochs = 2
forget = forgetting_scores(cfg_f, train_ds, mesh=mesh, sharder=sharder,
                           logger=MetricsLogger(None, echo=False))
# Never-learned examples sit at the sentinel (updates + 1), strictly above
# any possible event count (at most pretrain_epochs - 1 events).
print(f"forgetting: mean={forget.mean():.2f} events, "
      f"never-learned={(forget > cfg_f.score.pretrain_epochs).sum()}")

# %% AUM (Pleiss et al. 2020) rides the same trajectory hook: the mean
# probability margin across training epochs (higher = harder/mislabeled-ish).
from data_diet_distributed_tpu.train.loop import trajectory_scores

cfg_a = copy.deepcopy(cfg)
cfg_a.score.method = "aum"
cfg_a.score.pretrain_epochs = 2
aum = trajectory_scores(cfg_a, train_ds, mesh=mesh, sharder=sharder,
                        logger=MetricsLogger(None, echo=False))
print(f"aum: mean margin={aum.mean():+.3f}, "
      f"spearman(AUM, GraNd)={spearman(aum, grand):.3f}")

# %% The whole pipeline above is one config-driven call (or `datadiet run ...`);
# a sparsity sweep shares one scoring pass across levels (`datadiet sweep ...`):
# from data_diet_distributed_tpu.train.loop import run_datadiet, run_sweep
# summary = run_datadiet(cfg)
# summaries = run_sweep(cfg_with_prune_sweep)
